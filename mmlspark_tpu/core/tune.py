"""Tuner: the bounded measure -> refit -> apply loop over the cost model.

TVM's auto-tuning insight (arXiv:1802.04799) applied to this framework's
knobs: don't hand-tune constants, MEASURE candidate settings, refit the cost
model (core/costmodel.py), and keep what the measurements like. The knobs a
``Tuner`` owns are exactly the static heuristics the ROADMAP names:

  - shape-bucket sets per fused segment (``parallel/batching.py`` padded to
    powers of two today) — chosen to minimize predicted pad-waste plus
    recompile amortization over the observed batch-size histogram;
  - fuse-vs-demote per light segment (``core/fusion.py plan()``) — the
    predicted device-vs-host comparison, heuristic fallback when the model
    is not calibrated;
  - the adaptive batch controller's cold-start window (predicted compute ms
    seeds the EWMA — ``AdaptiveBatchController.seed_compute_ms``);
  - the serving executor's ``inflight`` depth and a ReplicaSet sizing
    suggestion, derived from predicted compute-vs-transfer overlap.

Every decision is journaled, every ``apply`` keeps the previous knob set,
and a measured regression past ``tolerance`` rolls back ONE step — the tuner
can never walk a server downhill. An UNCALIBRATED model proposes the empty
knob set, so cold-start behavior is bitwise-identical to the static
defaults. State (model + knobs + journal) serializes via ``to_dict``.

Two drive modes:

  - explicit: ``tuner.tune(measure)`` where ``measure() -> float`` is a
    higher-is-better end-to-end metric (qps, images/s) — the bench and
    offline calibration path;
  - serving: ``every=N`` makes ``on_epoch()`` (called by both serving
    loops after each batch) refit + apply every N batches and watch the
    measured per-batch e2e EWMA for regressions, rolling back one step
    when the tuned knobs made it worse.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import faults
from .costmodel import SegmentCostModel

__all__ = ["KnobSet", "Tuner"]


@dataclasses.dataclass
class KnobSet:
    """One coherent setting of every tuned knob. The default-constructed
    KnobSet IS the static-heuristic configuration (nothing overridden)."""

    #: per-segment-label shape-bucket sets (None entries impossible; absent
    #: label = keep the power-of-two default)
    buckets: Dict[str, Tuple[int, ...]] = dataclasses.field(
        default_factory=dict)
    #: per-segment-label fuse-vs-demote overrides for LIGHT segments
    fuse: Dict[str, bool] = dataclasses.field(default_factory=dict)
    #: predicted compute ms seeding the adaptive controller's EWMA
    window_seed_ms: Optional[float] = None
    #: executor in-flight slot depth
    inflight: Optional[int] = None
    #: ReplicaSet sizing suggestion (surfaced, not hot-applied: replica
    #: placement happens at server start)
    replicas: Optional[int] = None
    #: per-segment-label K-step mega-dispatch factors (absent label = K=1,
    #: the bitwise-identical single-step path)
    mega_k: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: per-segment-label partition-spec names over the fused model's mesh
    #: (parallel/shardplan.py; absent label = the single-device path)
    sharding: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: per-segment-label {bucket: kernel variant id} maps (core/kernels.py;
    #: absent label/bucket = the built-in default kernel)
    kernel_variants: Dict[str, Dict[str, str]] = dataclasses.field(
        default_factory=dict)
    #: per-stage-class-name cross-segment stitch flags (core/fusion.py
    #: plan(); absent name = never merge across that boundary)
    stitch: Dict[str, bool] = dataclasses.field(default_factory=dict)
    #: per-segment-label sparse staging layouts ("csr" stages capable
    #: sparse columns as wire triples, docs/sparse.md; absent label = the
    #: densify path, byte-for-byte the untuned behaviour)
    layout: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: pipeline depth for the fused plan's chainable segment run
    #: (parallel/pipeplan.py; None/<=1 = the serial path, byte-for-byte
    #: the untuned behaviour)
    pipe_depth: Optional[int] = None

    def is_default(self) -> bool:
        return not (self.buckets or self.fuse or self.mega_k or
                    self.sharding or self.kernel_variants or self.stitch or
                    self.layout or
                    self.window_seed_ms is not None or
                    self.inflight is not None or
                    self.replicas is not None or
                    self.pipe_depth is not None)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.buckets:
            out["buckets"] = {k: list(v) for k, v in self.buckets.items()}
        if self.fuse:
            out["fuse"] = dict(self.fuse)
        if self.mega_k:
            out["mega_k"] = {k: int(v) for k, v in self.mega_k.items()}
        if self.sharding:
            out["sharding"] = {k: str(v)
                               for k, v in self.sharding.items()}
        if self.kernel_variants:
            out["kernel_variants"] = {
                label: {str(b): str(v) for b, v in kv.items()}
                for label, kv in self.kernel_variants.items()}
        if self.stitch:
            out["stitch"] = {k: bool(v) for k, v in self.stitch.items()}
        if self.layout:
            out["layout"] = {k: str(v) for k, v in self.layout.items()}
        for k in ("window_seed_ms", "inflight", "replicas", "pipe_depth"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "KnobSet":
        return cls(
            buckets={k: tuple(int(x) for x in v)
                     for k, v in (d.get("buckets") or {}).items()},
            fuse={k: bool(v) for k, v in (d.get("fuse") or {}).items()},
            mega_k={k: int(v)
                    for k, v in (d.get("mega_k") or {}).items()},
            sharding={k: str(v)
                      for k, v in (d.get("sharding") or {}).items()},
            kernel_variants={
                label: {str(b): str(v) for b, v in (kv or {}).items()}
                for label, kv in (d.get("kernel_variants") or {}).items()},
            stitch={k: bool(v)
                    for k, v in (d.get("stitch") or {}).items()},
            layout={k: str(v)
                    for k, v in (d.get("layout") or {}).items()},
            window_seed_ms=d.get("window_seed_ms"),
            inflight=d.get("inflight"), replicas=d.get("replicas"),
            pipe_depth=d.get("pipe_depth"))


class Tuner:
    """Cost-model-driven knob tuner over a FusedPipelineModel (and,
    optionally, the serving executor/controller it runs under).

    ``fused``: the FusedPipelineModel whose CompileCache costs and
    per-segment IngestStats feed the model and whose ``set_tuning()``
    receives bucket/fuse knobs. ``controller``/``executor`` (wired by
    ``ServingServer.start()`` when the server owns a tuner): receive the
    window seed / inflight knobs. All optional — a Tuner over just a fused
    model tunes buckets and fuse decisions alone.
    """

    def __init__(self, fused=None, model: Optional[SegmentCostModel] = None,
                 controller=None, executor=None,
                 every: int = 0, tolerance: float = 0.05,
                 max_inflight: int = 8, journal_cap: int = 256):
        self.model = model if model is not None else SegmentCostModel()
        self.fused = fused
        self.controller = controller
        self.executor = executor
        #: serving mode: refit+apply every N batches (0 = explicit only)
        self.every = int(every)
        #: fractional e2e regression that triggers a one-step rollback
        self.tolerance = float(tolerance)
        self.max_inflight = int(max_inflight)
        self._journal_cap = int(journal_cap)
        self._lock = threading.Lock()
        self.knobs = KnobSet()
        self._prev: Optional[KnobSet] = None
        self.journal: List[Dict[str, Any]] = []
        self.applies = 0
        self.rollbacks = 0
        self.epochs = 0
        #: applies that changed the kernel_variants knob (the
        #: mmlspark_kernel_variant_switches_total counter)
        self.variant_switches = 0
        # incremental IngestStats folding: label -> (stats object id, fold
        # high-water mark) so re-reading a live stats object never double
        # counts records
        self._folded: Dict[str, Tuple[int, int]] = {}
        # serving-mode regression watch: per-batch e2e ms EWMAs before and
        # after the latest apply; the first post-apply batches are skipped
        # (they carry any fresh bucket's ONE-TIME XLA compile, which must
        # not read as a steady-state regression)
        self._e2e_before: Optional[float] = None
        self._e2e_after: Optional[float] = None
        self._e2e_after_n = 0
        self._e2e_skip = 0
        # a rolled-back knob set is vetoed for a few boundaries so a noisy
        # host doesn't flip-flop apply/rollback on the same proposal
        self._vetoed: Optional[Dict[str, Any]] = None
        self._veto_until = 0

    # -- journal ---------------------------------------------------------
    def _log(self, action: str, **fields: Any) -> None:
        entry = {"action": action, "epoch": self.epochs, **fields}
        with self._lock:
            self.journal.append(entry)
            if len(self.journal) > self._journal_cap:
                del self.journal[: self._journal_cap // 4]

    # -- refit -----------------------------------------------------------
    def fold_measured(self) -> None:
        """Fold the fused model's CURRENT per-segment IngestStats into the
        cost model (incremental, double-count safe). Called per batch in
        serving mode — the stats objects are replaced every transform, so
        waiting for the every-N refit would drop most of the records."""
        segs = getattr(self.fused, "_seg_stats", None) or {}
        for label, st in list(segs.items()):
            prev_id, mark = self._folded.get(label, (None, 0))
            if prev_id != id(st):
                mark = 0
            try:
                mark = self.model.observe_stats(label, st, start=mark)
            except Exception:  # noqa: BLE001
                continue
            self._folded[label] = (id(st), mark)

    def refit(self) -> None:
        """Fold the fused model's latest CompileCache costs and per-segment
        IngestStats into the cost model (incremental, double-count safe)."""
        fused = self.fused
        if fused is None:
            return
        cache = getattr(fused, "_cache", None)
        if cache is not None:
            try:
                self.model.ingest_costs(cache.costs())
            except Exception:  # noqa: BLE001 — refit must never kill serving
                pass
        self.fold_measured()

    # -- propose ---------------------------------------------------------
    def _segment_batch_caps(self) -> Dict[str, int]:
        """{segment label: configured batch size} over the fused plan."""
        out: Dict[str, int] = {}
        plan = getattr(self.fused, "_last_plan", None) or []
        for node in plan:
            bs = getattr(node, "batch_size", None)
            if callable(bs):
                out[node.label] = int(bs())
        return out

    def propose(self) -> KnobSet:
        """Derive a KnobSet from the current model. Uncalibrated segments
        contribute nothing, so a cold model proposes the default set."""
        knobs = KnobSet()
        caps = self._segment_batch_caps()
        trailing_ms: Optional[float] = None
        parts: Optional[Dict[str, float]] = None
        for label, cap in caps.items():
            if not self.model.calibrated(label):
                continue
            chosen = self.model.choose_buckets(label, cap)
            if chosen is not None:
                knobs.buckets[label] = chosen
            decision = self.model.fuse_decision(label)
            if decision is not None:
                knobs.fuse[label] = decision
            k = self._mega_k_for(label)
            if k is not None and k > 1:
                knobs.mega_k[label] = k
            spec = self._sharding_for(label, cap)
            if spec is not None:
                knobs.sharding[label] = spec
            variants = self._variants_for(label)
            if variants:
                knobs.kernel_variants[label] = variants
            lay = self._layout_for(label)
            if lay:
                knobs.layout[label] = lay
            pred = self.model.predict(label, batch=cap)
            if pred is not None:
                trailing_ms = pred["ms"]
                parts = pred.get("parts")
        stitch = self._stitch_proposals()
        if stitch:
            knobs.stitch = stitch
        depth = self._pipe_depth_for(caps)
        if depth is not None and depth > 1:
            knobs.pipe_depth = int(depth)
        if trailing_ms is not None:
            compute = (parts or {}).get("compute_ms")
            knobs.window_seed_ms = round(
                compute if compute is not None else trailing_ms, 4)
            transfer = sum((parts or {}).get(k, 0.0)
                           for k in ("h2d_ms", "readback_ms"))
            host = (parts or {}).get("dispatch_ms", 0.0)
            if compute and compute > 0:
                # slots needed so transfer+host hide behind compute
                knobs.inflight = max(1, min(
                    self.max_inflight,
                    1 + round((transfer + host) / compute)))
                knobs.replicas = self._replica_suggestion(compute, transfer)
        return knobs

    def _mega_k_for(self, label: str) -> Optional[int]:
        """Cost-model K for a segment, capped by observed queue depth (a K
        deeper than the queue ever gets only adds latency: the mega program
        would wait on batches that are not coming)."""
        chooser = getattr(self.model, "choose_mega_k", None)
        if not callable(chooser):
            return None
        try:
            k = chooser(label)
        except Exception:  # noqa: BLE001 — proposal must never raise out
            return None
        if k is None or k <= 1:
            return k
        depth = 0
        stats = getattr(self.fused, "_seg_stats", None) or {}
        st = stats.get(label)
        if st is not None:
            depth = int(getattr(st, "_occ_max", 0) or 0)
        if depth <= 0 and self.executor is not None:
            depth = int(getattr(self.executor, "inflight", 0) or 0)
        if depth > 0:
            k = min(k, depth)
        return max(1, k)

    def _sharding_for(self, label: str, cap: int) -> Optional[str]:
        """Cost-model partition-spec choice for one segment: enumerate the
        candidates the plan's stage graph admits over the fused model's
        mesh (parallel/shardplan.py), price each as flops/shards + the
        calibrated α·bytes collective term, and return the winner (None =
        stay unsharded — the default that keeps cold-start bitwise
        identical)."""
        mesh = getattr(self.fused, "shard_mesh", None)
        chooser = getattr(self.model, "choose_sharding", None)
        if mesh is None or not callable(chooser):
            return None
        seg = None
        for node in getattr(self.fused, "_last_plan", None) or []:
            if getattr(node, "label", None) == label and \
                    hasattr(node, "dfns"):
                seg = node
                break
        if seg is None:
            return None
        try:
            from ..parallel.shardplan import tuner_candidates

            cands = tuner_candidates(seg, mesh, model=self.model,
                                     batch=cap)
            if not cands:
                return None
            return chooser(label, cap, cands)
        except Exception:  # noqa: BLE001 — proposal must never raise out
            return None

    def _variants_for(self, label: str) -> Dict[str, str]:
        """Measured per-bucket kernel-variant winners for one segment
        (``costmodel.choose_variant`` over the buckets that hold trial
        data). {} proposes nothing — the built-in default kernels — which
        is also what a model without variant support yields."""
        chooser = getattr(self.model, "choose_variant", None)
        buckets = getattr(self.model, "variant_buckets", None)
        if not callable(chooser) or not callable(buckets):
            return {}
        out: Dict[str, str] = {}
        try:
            for b in buckets(label):
                vid = chooser(label, b)
                if vid:
                    out[str(b)] = str(vid)
        except Exception:  # noqa: BLE001 — proposal must never raise out
            return {}
        return out

    def _layout_for(self, label: str) -> Optional[str]:
        """Cost-model staging-layout choice for one segment ("csr" stages
        sparse columns as wire triples, docs/sparse.md). None — the
        densify default — from a model without nnz support, an
        uncalibrated nnz term, or bytes that do not favour CSR."""
        chooser = getattr(self.model, "choose_layout", None)
        if not callable(chooser):
            return None
        try:
            return chooser(label)
        except Exception:  # noqa: BLE001 — proposal must never raise out
            return None

    def _pipe_depth_for(self, caps: Dict[str, int]) -> Optional[int]:
        """Cost-model pipeline depth for the fused plan's longest
        chainable segment run (parallel/pipeplan.py ``chainable_runs`` +
        ``costmodel.choose_pipe_depth``). None — the serial default —
        without a mesh whose pipe axis is > 1, a >= 2-segment chainable
        run, or full calibration of every run member (the chooser's
        gate)."""
        mesh = getattr(self.fused, "shard_mesh", None)
        chooser = getattr(self.model, "choose_pipe_depth", None)
        if mesh is None or not callable(chooser):
            return None
        try:
            from ..parallel.mesh import PIPE_AXIS
            from ..parallel.pipeplan import chainable_runs, split_segments

            p = int(dict(getattr(mesh, "shape", {}) or {})
                    .get(PIPE_AXIS, 1))
            if p < 2:
                return None
            # propose over the PIPELINE VIEW of the plan — the same
            # re-cut build_pipe_plan will execute
            runs = chainable_runs(split_segments(
                getattr(self.fused, "_last_plan", None) or []))
            if not runs:
                return None
            run = max(runs, key=len)
            labels = [seg.label for _, seg in run]
            batch = min(int(caps.get(lab, 256)) for lab in labels)
            return chooser(labels, batch, p)
        except Exception:  # noqa: BLE001 — proposal must never raise out
            return None

    def _stitch_proposals(self) -> Dict[str, bool]:
        """Stitch flags for the plan's adjacent (Segment, Segment)
        boundaries split by a TERMINAL tail stage that carries a transpiled
        finalize shim (``stitchable`` + ``device_finalize`` +
        ``finalize_stitched``) and whose measured readback + H2D round-trip
        the cost model prices as worth removing (``stitch_decision``,
        calibration-gated — a cold model proposes nothing). Keys are the
        tail stage's class name — the same key ``plan()``'s
        ``stitch_overrides`` consumes."""
        decider = getattr(self.model, "stitch_decision", None)
        if not callable(decider):
            return {}
        out: Dict[str, bool] = {}
        nodes = getattr(self.fused, "_last_plan", None) or []
        for up, down in zip(nodes, nodes[1:]):
            if not (hasattr(up, "dfns") and hasattr(down, "dfns")):
                continue
            if not up.dfns or not down.dfns:
                continue
            tail = up.dfns[-1]
            if not (getattr(tail, "stitchable", False)
                    and getattr(tail, "device_finalize", None) is not None
                    and getattr(tail, "finalize_stitched", None)
                    is not None):
                continue
            try:
                decision = decider(up.label, down.label)
            except Exception:  # noqa: BLE001 — proposal must never raise
                continue
            if decision:
                out[type(up.stages[-1]).__name__] = True
        return out

    def predict_batch_ms(self, rows: int) -> Optional[float]:
        """Predicted wall ms for one serving batch of ``rows`` — the sum of
        the calibrated segments' batch predictions. None while uncalibrated
        (the serving watchdog stays on its measured-EWMA fallback). This is
        the cost-model side of the hung-dispatch budget
        (serving/supervisor.py DispatchWatchdog)."""
        total: Optional[float] = None
        for label in self._segment_batch_caps():
            if not self.model.calibrated(label):
                continue
            try:
                pred = self.model.predict(label, batch=int(rows))
            except Exception:  # noqa: BLE001 — prediction must never raise out
                continue
            if pred is not None and pred.get("ms") is not None:
                total = (total or 0.0) + float(pred["ms"])
        return total

    def predict_row_ms(self, bucket: int = 32) -> Optional[float]:
        """Per-ROW service estimate at ``bucket`` — the multimodel
        planner's packing key for THIS pipeline (the ``predict_ms`` mall
        hook). None while uncalibrated, so the mall falls back to its own
        measured EWMA (the probe-slot graduation path)."""
        if bucket <= 0:
            return None
        ms = self.predict_batch_ms(int(bucket))
        return None if ms is None else ms / int(bucket)

    def _replica_suggestion(self, compute_ms: float,
                            transfer_ms: float) -> Optional[int]:
        """Compute-bound segments scale across local devices; transfer-bound
        ones gain nothing from more replicas on one link."""
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return None
        try:
            n_dev = len(jax.local_devices())
        except Exception:  # noqa: BLE001 — backend init failure
            return None
        return n_dev if compute_ms >= transfer_ms else 1

    # -- apply / rollback ------------------------------------------------
    @staticmethod
    def _push(fused, knobs: KnobSet) -> None:
        """set_tuning with the full knob surface, degrading for older
        fused models (newest kwargs dropped first). ``pipe_depth`` ships
        as 1 when unset — set_tuning's <= 1 CLEARS the knob, so rolling
        back to a default set restores the serial path bitwise."""
        try:
            fused.set_tuning(buckets=knobs.buckets, fuse=knobs.fuse,
                             mega_k=knobs.mega_k, sharding=knobs.sharding,
                             kernel_variants=knobs.kernel_variants,
                             stitch=knobs.stitch, layout=knobs.layout,
                             pipe_depth=knobs.pipe_depth or 1)
        except TypeError:
            try:  # older fused models without the pipeline-depth knob
                fused.set_tuning(buckets=knobs.buckets, fuse=knobs.fuse,
                                 mega_k=knobs.mega_k,
                                 sharding=knobs.sharding,
                                 kernel_variants=knobs.kernel_variants,
                                 stitch=knobs.stitch, layout=knobs.layout)
            except TypeError:
                try:  # ... without the staging-layout knob either
                    fused.set_tuning(buckets=knobs.buckets,
                                     fuse=knobs.fuse,
                                     mega_k=knobs.mega_k,
                                     sharding=knobs.sharding,
                                     kernel_variants=knobs.kernel_variants,
                                     stitch=knobs.stitch)
                except TypeError:
                    Tuner._push_legacy(fused, knobs)

    @staticmethod
    def _push_legacy(fused, knobs: KnobSet) -> None:
        try:  # older fused models without the compiler-search knobs
            fused.set_tuning(buckets=knobs.buckets, fuse=knobs.fuse,
                             mega_k=knobs.mega_k,
                             sharding=knobs.sharding)
        except TypeError:
            try:  # ... without the sharding knob
                fused.set_tuning(buckets=knobs.buckets,
                                 fuse=knobs.fuse, mega_k=knobs.mega_k)
            except TypeError:  # ... or without the K knob either
                fused.set_tuning(buckets=knobs.buckets,
                                 fuse=knobs.fuse)

    def apply(self, knobs: KnobSet, reason: str = "apply") -> None:
        """Push a KnobSet into the wired layers, remembering the previous
        set for one-step rollback. A kernel-variant/stitch/layout swap
        that fails
        MID-SWAP (the ``tuner.kernel_apply`` chaos seam, or any push
        failure) restores the incumbent knob set — replies stay bitwise
        those of the incumbent variant."""
        with self._lock:
            prev = self.knobs
            self._prev = prev
            self.knobs = knobs
            self.applies += 1
            # serving watch: ignore the next batches' e2e (fresh-bucket
            # compile spike) before judging the new knobs
            self._e2e_skip = 2
        variant_change = knobs.kernel_variants != prev.kernel_variants
        swap_change = (variant_change or knobs.stitch != prev.stitch
                       or knobs.layout != prev.layout
                       or knobs.pipe_depth != prev.pipe_depth)
        fused = self.fused
        try:
            if swap_change:
                # chaos seam: a raise here lands MID-SWAP — tuner state
                # already points at the new knobs, the fused model still
                # runs the incumbent — the exact window the rollback
                # handler below must make safe
                faults.fire(faults.TUNER_KERNEL_APPLY)
            if fused is not None and hasattr(fused, "set_tuning"):
                self._push(fused, knobs)
        except Exception as e:  # noqa: BLE001 — a failed swap never serves
            with self._lock:
                self.knobs = prev
                self._prev = None  # the failed swap is not a step to redo
                self.rollbacks += 1
            if fused is not None and hasattr(fused, "set_tuning"):
                try:
                    self._push(fused, prev)  # re-pin the incumbent
                except Exception:  # noqa: BLE001
                    pass
            self._log("kernel_apply_rollback", error=str(e),
                      knobs=prev.to_dict())
            return
        if variant_change:
            with self._lock:
                self.variant_switches += 1
        if self.controller is not None and knobs.window_seed_ms is not None:
            seed = getattr(self.controller, "seed_compute_ms", None)
            if callable(seed):
                seed(knobs.window_seed_ms)
        if self.executor is not None and knobs.inflight is not None:
            set_inflight = getattr(self.executor, "set_inflight", None)
            if callable(set_inflight):
                set_inflight(knobs.inflight)
        self._log(reason, knobs=knobs.to_dict())

    def warm_start(self, knobs: Dict[str, Any]) -> bool:
        """Apply a SHIPPED knob snapshot (fleet/objstore.py knob shipping)
        at serve start: the fresh pod begins on the fleet's tuned
        buckets/mega-K/sharding/variants with no relearning window.
        Journaled as ``warm_start`` with one-step rollback to the defaults
        this pod would otherwise have started on. False (and untouched
        state) on an empty, default, or malformed snapshot."""
        try:
            ks = KnobSet.from_dict(dict(knobs or {}))
        except Exception:  # noqa: BLE001 — a bad snapshot just relearns
            return False
        if ks.is_default():
            return False
        self.apply(ks, reason="warm_start")
        return True

    def rollback(self, reason: str = "regression") -> bool:
        """Re-apply the PREVIOUS knob set (one step). Returns False when
        there is nothing to roll back to."""
        with self._lock:
            prev = self._prev
            if prev is None:
                return False
            self._prev = None
        self.apply(prev, reason=f"rollback:{reason}")
        with self._lock:
            self.rollbacks += 1
            self._prev = None  # a rollback is terminal for that step
        return True

    # -- explicit tuning loop --------------------------------------------
    def _measure(self, measure: Callable[[], float]) -> float:
        # chaos seam: an injected delay here slows THIS measurement (the
        # deterministic way to fake a regression in tests); an injected
        # exception surfaces to the caller like any measurement failure
        t0 = time.perf_counter()
        faults.fire(faults.TUNER_MEASURE)
        penalty = time.perf_counter() - t0
        value = float(measure())
        if penalty > 0:
            # an injected stall IS a slower system: scale the
            # higher-is-better metric down by the stalled fraction
            value = value / (1.0 + penalty)
        return value

    def tune(self, measure: Callable[[], float], steps: int = 1,
             warmup: int = 1) -> Dict[str, Any]:
        """Bounded measure -> refit -> apply loop. ``measure() -> float``
        is higher-is-better end-to-end goodness (qps, images/s); it should
        exercise the fused pipeline so refit() sees fresh stats. A step
        whose measurement regresses past ``tolerance`` rolls back and the
        loop stops (one-step rollback contract). ``warmup`` discarded
        measure() calls follow each apply so a fresh bucket's ONE-TIME XLA
        compile doesn't read as a steady-state regression (compile cost is
        already charged in the model's bucket scoring, amortized over
        ``compile_horizon``). Returns the decision summary (journaled)."""
        baseline = self._measure(measure)
        self._log("baseline", value=round(baseline, 6))
        history = [{"step": 0, "value": round(baseline, 6),
                    "knobs": self.knobs.to_dict(), "accepted": True}]
        for step in range(1, max(1, int(steps)) + 1):
            self.refit()
            knobs = self.propose()
            self.apply(knobs)
            for _ in range(max(0, int(warmup))):
                measure()  # discarded: compiles fresh-bucket executables
            value = self._measure(measure)
            accepted = value >= baseline * (1.0 - self.tolerance)
            entry = {"step": step, "value": round(value, 6),
                     "knobs": knobs.to_dict(), "accepted": accepted}
            history.append(entry)
            if not accepted:
                self.rollback("tune_step_regressed")
                self._log("tune_step", **entry)
                break
            self._log("tune_step", **entry)
            baseline = max(baseline, value)
        return {"baseline": history[0]["value"], "steps": history,
                "final_knobs": self.knobs.to_dict(),
                "rollbacks": self.rollbacks}

    # -- serving integration ---------------------------------------------
    def on_batch(self, e2e_s: float) -> None:
        """Feed one served batch's end-to-end seconds (queue+compute+
        readback) — the regression signal for serving-mode tuning. Batches
        right after an apply are skipped: they carry any fresh bucket's
        one-time compile, not steady state."""
        ms = float(e2e_s) * 1e3
        with self._lock:
            if self._e2e_skip > 0:
                self._e2e_skip -= 1
                return
            if self._e2e_after is None:
                self._e2e_after = ms
            else:
                self._e2e_after = 0.75 * self._e2e_after + 0.25 * ms
            self._e2e_after_n += 1

    def on_epoch(self, e2e_s: Optional[float] = None) -> None:
        """Per-batch tick from the serving loops. Every ``self.every``
        batches: check the post-apply e2e EWMA against the pre-apply one
        (rollback on regression), then refit and apply a fresh proposal."""
        if e2e_s is not None:
            self.on_batch(e2e_s)
        self.fold_measured()
        with self._lock:
            self.epochs += 1
            if self.every <= 0 or self.epochs % self.every != 0:
                return
            before, after = self._e2e_before, self._e2e_after
            enough = self._e2e_after_n >= max(2, self.every // 2)
        if (before is not None and after is not None and enough
                and self._prev is not None
                and after > before * (1.0 + self.tolerance)):
            bad = self.knobs.to_dict()
            self.rollback("serving_e2e_regressed")
            with self._lock:
                self._vetoed = bad
                self._veto_until = self.epochs + 4 * max(1, self.every)
                self._e2e_before = after
                self._e2e_after = None
                self._e2e_after_n = 0
            return
        self.refit()
        knobs = self.propose()
        kd = knobs.to_dict()
        with self._lock:
            vetoed = (self._vetoed is not None and kd == self._vetoed
                      and self.epochs < self._veto_until)
        if kd == self.knobs.to_dict():
            self._log("steady", knobs=kd)
        elif vetoed:
            # the measured watch rejected exactly this set recently: hold
            # the current knobs until the veto window passes
            self._log("vetoed", knobs=kd)
        else:
            self.apply(knobs)
        with self._lock:
            self._e2e_before = after if after is not None else before
            self._e2e_after = None
            self._e2e_after_n = 0

    # -- stats / serialization -------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """The ``tuner`` section of /_mmlspark/stats (and the source of the
        mmlspark_tuner_* metric families, obs/bridge.py)."""
        with self._lock:
            journal = list(self.journal[-16:])
            knob_ref = self.knobs
            applies, rollbacks, epochs = \
                self.applies, self.rollbacks, self.epochs
            switches = self.variant_switches
            e2e = {"before_ms": self._e2e_before,
                   "after_ms": self._e2e_after}
        knobs = knob_ref.to_dict()
        out = {
            "every": self.every, "tolerance": self.tolerance,
            "epochs": epochs, "applies": applies, "rollbacks": rollbacks,
            "calibrated": self.model.calibrated(),
            "knobs": knobs, "default_knobs": KnobSet().to_dict(),
            "knobs_active": not KnobSet.from_dict(knobs).is_default(),
            "predicted_vs_measured": self.model.prediction_error(),
            "model": self.model.stats(),
            "e2e_ewma": e2e,
            "journal": journal,
        }
        if switches:  # key absent until a variant ever switched: parity
            out["variant_switches"] = switches
        return out

    def to_dict(self) -> Dict[str, Any]:
        # snapshot the model OUTSIDE our lock: it takes its own (single
        # consistent lock order — model never calls back into the tuner)
        model = self.model.to_dict()
        with self._lock:
            knob_ref = self.knobs
            out = {"version": 1, "every": self.every,
                   "tolerance": self.tolerance,
                   "applies": self.applies, "rollbacks": self.rollbacks,
                   "epochs": self.epochs,
                   "journal": list(self.journal),
                   "model": model}
            if self.variant_switches:
                out["variant_switches"] = self.variant_switches
        out["knobs"] = knob_ref.to_dict()
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any], fused=None, controller=None,
                  executor=None) -> "Tuner":
        t = cls(fused=fused, controller=controller, executor=executor,
                model=SegmentCostModel.from_dict(d.get("model") or {}),
                every=int(d.get("every", 0)),
                tolerance=float(d.get("tolerance", 0.05)))
        t.knobs = KnobSet.from_dict(d.get("knobs") or {})
        t.applies = int(d.get("applies", 0))
        t.rollbacks = int(d.get("rollbacks", 0))
        t.epochs = int(d.get("epochs", 0))
        t.variant_switches = int(d.get("variant_switches", 0))
        t.journal = list(d.get("journal") or [])
        return t
