"""Pipeline stage contract: Transformer / Estimator / Model / Pipeline.

Re-design of SparkML's stage algebra that the whole reference is expressed in
(SURVEY §1: "Everything is expressed as SparkML Transformer/Estimator stages operating
on DataFrames"). Stages carry typed params (core/params.py), operate column-to-column
on the partitioned columnar DataFrame (core/dataframe.py), and persist via
core/serialize.py (ComplexParamsWritable parity).

Class registry: every concrete stage subclass auto-registers by qualified name so
save/load can reconstruct stages from metadata (reference: Spark's
DefaultParamsReader.loadParamsInstance class-name dispatch).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Type

from .dataframe import DataFrame
from .params import Params
from .schema import Schema

_STAGE_REGISTRY: Dict[str, Type["PipelineStage"]] = {}


def get_stage_class(name: str) -> Type["PipelineStage"]:
    if name in _STAGE_REGISTRY:
        return _STAGE_REGISTRY[name]
    short = name.rsplit(".", 1)[-1]
    if short in _STAGE_REGISTRY:
        return _STAGE_REGISTRY[short]
    raise KeyError(f"Unknown stage class '{name}'. Registered: {sorted(_STAGE_REGISTRY)}")


def registered_stages() -> Dict[str, Type["PipelineStage"]]:
    """All registered stage classes — drives codegen + fuzzing coverage enforcement
    (reference: FuzzingTest reflection over the jar, core/test/fuzzing/FuzzingTest.scala)."""
    return dict(_STAGE_REGISTRY)


class PipelineStage(Params):
    """Base of all stages. Subclasses auto-register for persistence/codegen."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        # `_abstract = True` in the class's own dict marks intermediate bases
        # that are not user-constructible stages (kept out of the registry so
        # codegen and fuzzing enforcement see only concrete stages)
        if not cls.__name__.startswith("_") and \
                not cls.__dict__.get("_abstract", False):
            _STAGE_REGISTRY[cls.__name__] = cls
            _STAGE_REGISTRY[f"{cls.__module__}.{cls.__name__}"] = cls

    @property
    def uid(self) -> str:
        if not hasattr(self, "_uid"):
            self._uid = f"{type(self).__name__}_{id(self):x}"
        return self._uid

    def transform_schema(self, schema: Schema) -> Schema:
        """Schema-only validation/propagation hook. Default: identity."""
        return schema

    def device_fn(self, schema: Schema):
        """Device-stage contract hook (core/device_stage.py): return a
        ``DeviceFn`` describing this stage as a jittable column program so
        the fusion planner (core/fusion.py) can compile it into a shared
        XLA program with its neighbors, or None (default) for host-only
        stages. Implementations must keep the bitwise contract: fused
        output == unfused output on every partition the DeviceFn accepts."""
        return None

    # persistence (implemented in serialize.py to avoid circular imports)
    def save(self, path: str, overwrite: bool = True) -> None:
        from .serialize import save_stage
        save_stage(self, path, overwrite=overwrite)

    @staticmethod
    def load(path: str) -> "PipelineStage":
        from .serialize import load_stage
        return load_stage(path)


class Transformer(PipelineStage):
    """A DataFrame -> DataFrame stage."""

    _abstract = True

    def transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Estimator(PipelineStage):
    """A stage fitted on a DataFrame, producing a Model."""

    _abstract = True

    def fit(self, df: DataFrame) -> "Model":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer (may reference its parent estimator's params)."""

    _abstract = True


class Evaluator(Params):
    """Scores a transformed DataFrame with a single metric (SparkML Evaluator parity)."""

    def evaluate(self, df: DataFrame) -> float:
        raise NotImplementedError

    def is_larger_better(self) -> bool:
        return True


class Pipeline(Estimator):
    """Sequential composition of stages (SparkML Pipeline parity).

    fit() runs stages in order: Transformers transform-through, Estimators fit on the
    current data then transform with the fitted model. Produces a PipelineModel.
    """

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, **kwargs):
        super().__init__(**kwargs)
        self._stages: List[PipelineStage] = list(stages or [])

    @property
    def stages(self) -> List[PipelineStage]:
        return self._stages

    def set_stages(self, stages: Sequence[PipelineStage]) -> "Pipeline":
        self._stages = list(stages)
        return self

    def fit(self, df: DataFrame) -> "PipelineModel":
        fitted: List[Transformer] = []
        cur = df
        for i, stage in enumerate(self._stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                if i < len(self._stages) - 1:
                    cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < len(self._stages) - 1:
                    cur = stage.transform(cur)
            else:
                raise TypeError(f"Pipeline stage {stage!r} is neither Transformer nor Estimator")
        return PipelineModel(fitted)

    def transform_schema(self, schema: Schema) -> Schema:
        for s in self._stages:
            schema = s.transform_schema(schema)
        return schema


class PipelineModel(Model):
    """Fitted pipeline: a chain of Transformers.

    Also the product of NamespaceInjections.pipelineModel in the reference
    (org/apache/spark/ml/NamespaceInjections.scala:1-23) — construct directly
    from a list of transformers without fitting.
    """

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, **kwargs):
        super().__init__(**kwargs)
        self._stages: List[Transformer] = list(stages or [])

    @property
    def stages(self) -> List[Transformer]:
        return self._stages

    def transform(self, df: DataFrame, fused: bool = False) -> DataFrame:
        if fused:
            return self.fuse().transform(df)
        for s in self._stages:
            df = s.transform(df)
        return df

    def fuse(self) -> "PipelineModel":
        """Compile adjacent device-capable stages into shared XLA programs
        (core/fusion.py). Returns a FusedPipelineModel whose transform is
        bitwise-identical to this chain but keeps intermediates on device
        across stage boundaries; host-only stages still run per-stage.
        The fused runner is cached — repeated fuse() calls share compiled
        executables."""
        if getattr(self, "_fused_runner", None) is None:
            from .fusion import FusedPipelineModel

            self._fused_runner = FusedPipelineModel(self._stages)
        return self._fused_runner

    def transform_schema(self, schema: Schema) -> Schema:
        for s in self._stages:
            schema = s.transform_schema(schema)
        return schema


def pipeline_model(*stages: Transformer) -> PipelineModel:
    """NamespaceInjections.pipelineModel parity helper."""
    return PipelineModel(list(stages))
