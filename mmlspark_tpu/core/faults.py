"""Unified fault-tolerance layer: retry policy, deadlines, fault injection.

The reference leans on Spark task retry + epoch replay for resilience
(HTTPSourceV2's epoch machinery, `FaultToleranceUtils.retryWithTimeout`);
the TPU-native stack has no scheduler to lean on, so the equivalent contract
is a framework-level layer (the Automap argument, arxiv 2112.02958: cross-
cutting machinery belongs in the framework, not per-stage ad-hoc code):

  - ``RetryPolicy``    — jittered exponential backoff with a total sleep
    budget and deadline awareness; adopted by io/http.send_with_retries,
    cognitive/base, serving/routing health probes, and downloader retries.
  - ``Deadline``       — absolute wall-clock deadline carried end-to-end in
    the ``X-MMLSpark-Deadline`` header (epoch seconds): expired requests are
    dropped pre-transform with 504 instead of burning a batch slot.
  - ``FaultInjector``  — deterministic, seedable chaos: named injection
    points (HTTP send, worker forward, ingest H2D, journal write/commit,
    train step) so a chaos scenario replays EXACTLY under a fixed seed.
  - atomic-file helpers (tmp + rename + fsync, EXDEV-safe rename) shared by
    the journal compactor, GBDT checkpoints, and the model downloader.

See docs/faults.md for the resilience contract.
"""

from __future__ import annotations

import dataclasses
import errno
import os
import random
import shutil
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------

#: header carrying the absolute request deadline (unix epoch seconds, float)
DEADLINE_HEADER = "X-MMLSpark-Deadline"


class Deadline:
    """Absolute wall-clock deadline (epoch seconds). Propagates across
    machines via ``X-MMLSpark-Deadline`` — absolute time, not a countdown, so
    queue/transfer delays between hops count against it."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = float(at)

    @classmethod
    def from_timeout(cls, seconds: float) -> "Deadline":
        return cls(time.time() + seconds)

    @staticmethod
    def from_header(value: Optional[str]) -> Optional["Deadline"]:
        if not value:
            return None
        try:
            return Deadline(float(value))
        except (TypeError, ValueError):
            return None

    def to_header(self) -> str:
        return repr(self.at)

    def remaining(self) -> float:
        return max(0.0, self.at - time.time())

    def expired(self) -> bool:
        return time.time() >= self.at

    def cap(self, wait: float) -> float:
        """Clamp a candidate sleep/timeout to the time left."""
        return min(wait, self.remaining())

    def __repr__(self) -> str:  # pragma: no cover - debugging sugar
        return f"Deadline(at={self.at!r}, remaining={self.remaining():.3f}s)"


def deadline_from_headers(headers: Optional[Mapping[str, str]]
                          ) -> Optional[Deadline]:
    """Case-insensitive ``X-MMLSpark-Deadline`` lookup on any mapping
    (http.client message objects and plain dicts alike)."""
    if not headers:
        return None
    get = getattr(headers, "get", None)
    if get is not None:
        v = get(DEADLINE_HEADER) or get(DEADLINE_HEADER.lower())
        if v is not None:
            return Deadline.from_header(v)
    low = DEADLINE_HEADER.lower()
    for k in headers:
        if str(k).lower() == low:
            return Deadline.from_header(headers[k])
    return None


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff with a retry budget and deadline cap.

    ``jitter`` is the +/- fraction applied to each backoff (0.2 => +/-20%);
    with ``seed`` set the jitter stream is deterministic (chaos replay).
    ``budget_s`` bounds the TOTAL time slept across all retries of one call.
    """

    max_retries: int = 3
    base_s: float = 0.1
    multiplier: float = 2.0
    max_backoff_s: float = 30.0
    jitter: float = 0.2
    budget_s: Optional[float] = None
    seed: Optional[int] = None

    def make_rng(self) -> random.Random:
        return random.Random(self.seed)  # Random(None) seeds from entropy

    def next_wait(self, attempt: int,
                  rng: Optional[random.Random] = None) -> float:
        """Backoff for ``attempt`` (0-based), jittered."""
        base = min(self.base_s * (self.multiplier ** attempt),
                   self.max_backoff_s)
        if self.jitter <= 0:
            return base
        r = rng if rng is not None else self.make_rng()
        return max(0.0, base * (1.0 + self.jitter * r.uniform(-1.0, 1.0)))

    def backoffs(self, deadline: Optional[Deadline] = None):
        """Yield up to ``max_retries`` jittered waits, stopping early when the
        sleep budget or the deadline is exhausted. Each yielded wait is
        already capped at the remaining budget/deadline."""
        rng = self.make_rng()
        spent = 0.0
        for attempt in range(self.max_retries):
            wait = self.next_wait(attempt, rng)
            if self.budget_s is not None:
                left = self.budget_s - spent
                if left <= 0:
                    return
                wait = min(wait, left)
            if deadline is not None:
                left = deadline.remaining()
                if left <= 0:
                    return
                wait = min(wait, left)
            spent += wait
            yield wait

    def run(self, fn: Callable[[], Any], *,
            should_retry: Callable[[BaseException], bool] = lambda e: True,
            deadline: Optional[Deadline] = None,
            sleep_fn: Callable[[float], None] = time.sleep) -> Any:
        """Call ``fn`` with retries; re-raises the last error when the retry
        budget / deadline / attempt count is exhausted."""
        last: Optional[BaseException] = None
        waits = self.backoffs(deadline)
        while True:
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 - policy decides
                last = e
                if not should_retry(e):
                    raise
            try:
                wait = next(waits)
            except StopIteration:
                raise last
            sleep_fn(wait)


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------

# Named injection points (the instrumented seams of the framework):
HTTP_SEND = "http.send"            # io/http.send_request, before the socket
WORKER_FORWARD = "worker.forward"  # serving/routing forward-to-worker
INGEST_H2D = "ingest.h2d"          # parallel/ingest TransferRing staging
JOURNAL_WRITE = "journal.write"    # serving/journal entry append
JOURNAL_COMMIT = "journal.commit"  # serving/journal epoch commit
TRAIN_STEP = "train.step"          # gbdt boosting iteration / DNN train step
TUNER_MEASURE = "tuner.measure"    # core/tune Tuner's e2e measurement probe
# serving/executor replica compute loop, just before dispatch: plan with
# delay_s + exc=None to wedge a dispatch (the watchdog's deterministic prey)
WORKER_DISPATCH_HANG = "worker.dispatch_hang"
# serving/executor replica compute loop: a raising plan simulates a replica
# process crash mid-dispatch (feeds the supervisor's error scoring)
WORKER_CRASH = "worker.crash"
# serving/routing hedge launch (threaded + async fronts): a raising plan
# suppresses that hedge; fired() observes exactly which requests hedged
FRONT_HEDGE = "front.hedge"
# serving/fleet persistent compile-cache tier: load fires before an entry
# is read/deserialized (a raising plan = corrupted/unreadable entry ->
# accounted recompile); store fires before the atomic write (a raising
# plan = full/readonly cache volume -> serving continues uncached)
COMPILECACHE_LOAD = "compilecache.load"
COMPILECACHE_STORE = "compilecache.store"
# parallel/shardplan SegmentSharding.device_put, before a host batch is
# staged across the mesh: a raising plan simulates a chip dropping out of
# its shard group mid-stage (the executor degrades that dispatch to the
# host fallback; MeshSupervision quarantines the GROUP and re-plans onto
# the surviving submesh); delay_s wedges the sharded dispatch for the
# mesh-aware watchdog. Fires on the SHARDED path only — unsharded
# bitwise-parity is never perturbed by an armed plan.
MESH_CHIP_WEDGE = "mesh.chip_wedge"
# serving/lifecycle registry swap_live, fired BEFORE any registry or
# executor state mutates: a raising plan is a crash mid-swap and must
# leave the incumbent serving with the candidate un-promoted
LIFECYCLE_SWAP = "lifecycle.swap"
# serving/lifecycle OnlineTrainer, fired before the atomic checkpoint
# write: a raising plan crashes training at checkpoint k; resume() +
# journal replay must reproduce the uninterrupted state bitwise
LIFECYCLE_CHECKPOINT = "lifecycle.checkpoint"
# core/tune Tuner.apply, fired MID-SWAP of a kernel-variant/stitch knob
# change (tuner state updated, fused model not yet pushed): a raising plan
# must leave the incumbent variant serving bitwise-identical replies
TUNER_KERNEL_APPLY = "tuner.kernel_apply"
# serving/fabric L1 front forwarding to an L2 cell (fires only when the
# fabric is enabled, just before the forward): a raising plan is a cell
# dying mid-request — InjectedFault reads as a connection-class "error"
# (replay-safe), so the L1 re-hashes the tenant onto the survivor and the
# reply must be bitwise-identical to a single-front retry
FRONT_L2_CRASH = "front.l2_crash"
# serving/fabric ring membership change, fired BEFORE the epoch mutates:
# a raising plan is a crash mid-rebalance and must leave the journaled
# previous epoch serving (membership, points and epoch all unchanged)
RING_REBALANCE = "ring.rebalance"
# serving/fleet object store put/get, fired before the backend I/O: a
# raising put is a full/unreachable store (tier degrades to accounted
# read-only, serving continues uncached); a raising get is a corrupted /
# unavailable object (accounted recompile, exactly like PR 13)
STORE_PUT = "store.put"
STORE_GET = "store.get"
# core/fusion CSR staging, fired before a sparse column is assembled into
# its (indptr, indices, values) wire triple: a raising plan degrades THAT
# column to the accounted densify fallback (IngestStats.note_densify) —
# output stays bitwise-equal to the dense path, the waste is just counted.
# Fires on the CSR path only — densify-path parity is never perturbed.
SPARSE_STAGE = "sparse.stage"
# parallel/pipeplan PipeRunner, fired per micro-batch before each stage's
# dispatch (ctx: stage=<index>): a raising plan simulates a stage's whole
# sub-mesh dropping out mid-stream — the model quarantines the stage and
# re-plans at depth N-1 over the surviving sub-meshes, re-running the
# in-flight partition (no request dropped); delay_s wedges the stream for
# the watchdog. Fires on the PIPELINED path only — with the pipe_depth
# knob off an armed plan never perturbs the serial bitwise-parity path.
PIPE_STAGE_WEDGE = "pipe.stage_wedge"
# serving/multimodel ModelMall trial/version promotion, fired BEFORE the
# per-model registry swap mutates (the mall's analogue of LIFECYCLE_SWAP,
# with model= in the context): a raising plan is a crash mid-promotion and
# must leave the model's incumbent version serving bitwise
MALL_SWAP = "mall.swap"
# serving/multimodel ModelMall cold-model eviction, fired AFTER the plane
# is parked to the persistent/object-store tier but BEFORE the resident
# copy is dropped: a raising plan is a crash mid-evict — the resident copy
# is lost either way, but the tier copy (written first) survives, so the
# model stays servable through an accounted re-warm on its next request;
# a model is never stranded half-evicted
MALL_EVICT = "mall.evict"

ALL_POINTS = (HTTP_SEND, WORKER_FORWARD, INGEST_H2D, JOURNAL_WRITE,
              JOURNAL_COMMIT, TRAIN_STEP, TUNER_MEASURE,
              WORKER_DISPATCH_HANG, WORKER_CRASH, FRONT_HEDGE,
              COMPILECACHE_LOAD, COMPILECACHE_STORE, MESH_CHIP_WEDGE,
              LIFECYCLE_SWAP, LIFECYCLE_CHECKPOINT, TUNER_KERNEL_APPLY,
              FRONT_L2_CRASH, RING_REBALANCE, STORE_PUT, STORE_GET,
              SPARSE_STAGE, PIPE_STAGE_WEDGE, MALL_SWAP, MALL_EVICT)


class InjectedFault(OSError):
    """Raised by an armed injection point. Subclasses OSError so transport-
    level seams (worker forward, HTTP send) treat it as a connection-class
    failure and exercise their real retry/eviction paths."""


class InjectedDiskFull(InjectedFault):
    """Injected fault carrying ``errno.ENOSPC``: plan with ``exc=
    InjectedDiskFull`` at a write seam (``store.put``, ``journal.write``)
    to drive the disk-full degrade path — the consumer must flip to
    accounted read-only mode, never crash the serving loop."""

    def __init__(self, *args: Any):
        super().__init__(*args)
        self.errno = errno.ENOSPC


@dataclasses.dataclass
class FaultSpec:
    """One armed fault: fire on exact call indices (``at``, 1-based), every
    Nth call (``every``), or with probability ``p`` (seeded — deterministic).
    ``times`` caps total fires (-1 = unlimited). ``delay_s`` sleeps at the
    point; ``exc`` (when not None) then raises."""

    point: str
    at: Tuple[int, ...] = ()
    every: int = 0
    p: float = 0.0
    times: int = -1
    delay_s: float = 0.0
    exc: Optional[type] = InjectedFault
    message: str = ""


class FaultInjector:
    """Deterministic, seedable chaos driver.

    Usage::

        with FaultInjector(seed=7).plan(faults.WORKER_FORWARD, at=(1,)):
            ...   # first worker forward fails with InjectedFault

    Same seed + same plan => the identical fault sequence, so a chaos
    scenario replays exactly. Thread-safe: counters are lock-guarded (the
    instrumented seams run on server/producer threads).
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._specs: Dict[str, FaultSpec] = {}
        self._calls: Dict[str, int] = {}
        self._fires: Dict[str, int] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._log: List[Tuple[str, int, Dict[str, Any]]] = []
        self._lock = threading.Lock()
        self._prev: Optional["FaultInjector"] = None

    def plan(self, point: str, *, at: Tuple[int, ...] = (), every: int = 0,
             p: float = 0.0, times: int = -1, delay_s: float = 0.0,
             exc: Optional[type] = InjectedFault,
             message: str = "") -> "FaultInjector":
        self._specs[point] = FaultSpec(point, tuple(at), every, p, times,
                                       delay_s, exc, message)
        # per-point deterministic stream: stable across runs and independent
        # of arming order
        self._rngs[point] = random.Random(
            self.seed ^ zlib.crc32(point.encode("utf-8")))
        return self

    # -- firing (called from instrumented library code via module fire()) --
    def check(self, point: str, **ctx: Any) -> None:
        spec = self._specs.get(point)
        if spec is None:
            return
        with self._lock:
            n = self._calls.get(point, 0) + 1
            self._calls[point] = n
            should = False
            if spec.times < 0 or self._fires.get(point, 0) < spec.times:
                if spec.at and n in spec.at:
                    should = True
                elif spec.every and n % spec.every == 0:
                    should = True
                elif spec.p > 0 and self._rngs[point].random() < spec.p:
                    should = True
            if should:
                self._fires[point] = self._fires.get(point, 0) + 1
                self._log.append((point, n, dict(ctx)))
        if not should:
            return
        if spec.delay_s > 0:
            time.sleep(spec.delay_s)
        if spec.exc is not None:
            raise spec.exc(spec.message
                           or f"injected fault at {point!r} (call #{n})")

    # -- introspection -----------------------------------------------------
    def fired(self, point: Optional[str] = None
              ) -> List[Tuple[str, int, Dict[str, Any]]]:
        with self._lock:
            return [e for e in self._log if point is None or e[0] == point]

    def calls(self, point: str) -> int:
        with self._lock:
            return self._calls.get(point, 0)

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> "FaultInjector":
        global _ACTIVE
        self._prev = _ACTIVE
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        _ACTIVE = self._prev
        self._prev = None

    def __enter__(self) -> "FaultInjector":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


_ACTIVE: Optional[FaultInjector] = None


def active() -> Optional[FaultInjector]:
    return _ACTIVE


def fire(point: str, **ctx: Any) -> None:
    """Injection-point hook: no-op unless a FaultInjector is installed (one
    None check on the hot path)."""
    inj = _ACTIVE
    if inj is not None:
        inj.check(point, **ctx)


# ---------------------------------------------------------------------------
# Atomic file helpers (shared by journal compaction, GBDT checkpoints,
# downloader staging)
# ---------------------------------------------------------------------------


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss. Best
    effort: some filesystems/platforms reject O_RDONLY dir fsync."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_text(path: str, text: str) -> None:
    """Durable atomic file write: tmp in the same directory + flush + fsync +
    rename + directory fsync. A crash at any point leaves either the old
    complete file or the new complete file — never a torn one."""
    d = os.path.dirname(os.path.abspath(path))
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(text)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    fsync_dir(d)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Binary sibling of :func:`atomic_write_text`: tmp in the same
    directory + flush + fsync + rename + directory fsync (used by the
    model downloader's remote fetch path)."""
    d = os.path.dirname(os.path.abspath(path))
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    fsync_dir(d)


def rename_with_exdev_fallback(src: str, dst: str,
                               _rename: Callable[[str, str], None] = os.rename
                               ) -> None:
    """``os.rename`` that degrades to copy + same-filesystem rename when src
    and dst live on different filesystems (EXDEV) — staging dirs on tmpfs,
    destinations on a persistent volume. The final hop into ``dst`` is still
    an atomic rename on dst's filesystem."""
    try:
        _rename(src, dst)
        return
    except OSError as e:
        if e.errno != errno.EXDEV:
            raise
    stage = f"{dst}.xdev.{os.getpid()}"
    try:
        if os.path.isdir(src):
            shutil.copytree(src, stage)
        else:
            shutil.copy2(src, stage)
        os.rename(stage, dst)
    except BaseException:
        if os.path.isdir(stage):
            shutil.rmtree(stage, ignore_errors=True)
        else:
            try:
                os.remove(stage)
            except OSError:
                pass
        raise
    if os.path.isdir(src):
        shutil.rmtree(src, ignore_errors=True)
    else:
        try:
            os.remove(src)
        except OSError:
            pass
