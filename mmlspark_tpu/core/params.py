"""Typed parameter system for pipeline stages.

TPU-native re-design of the reference's SparkML param layer:
  - ``Param``/``Params``  ~ org.apache.spark.ml.param + core/contracts/Params.scala:9-177
  - ``ComplexParam``      ~ core/serialize/ComplexParam.scala:13-35 (params holding non-JSON
    objects: weights, models, functions, DataFrames), persisted by the stage serializer.
  - ``ServiceParam``      ~ cognitive/CognitiveServiceBase.scala:29-151 (value-or-column).

Unlike the JVM reference there is no reflection-based codegen step needed for Python —
stages ARE Python — but the same metadata (`Params.params`) drives doc generation and the
fuzzing test harness (tests enforce every stage exposes its params).
"""

from __future__ import annotations

import copy as _copy
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Type


class Param:
    """A named, documented, typed parameter attached to a stage class.

    Mirrors org.apache.spark.ml.param.Param (reference core/contracts/Params.scala): a
    JSON-serializable value with a validator. Non-JSON values belong in ComplexParam.
    """

    def __init__(
        self,
        name: str,
        doc: str = "",
        default: Any = None,
        validator: Optional[Callable[[Any], bool]] = None,
        ptype: Optional[type] = None,
    ):
        self.name = name
        self.doc = doc
        self.default = default
        self.validator = validator
        self.ptype = ptype
        self.is_complex = False
        self.is_service = False

    def validate(self, value: Any) -> None:
        if value is None:
            return
        if self.ptype is not None:
            if self.ptype is float and isinstance(value, int) and not isinstance(value, bool):
                value = float(value)
            elif not isinstance(value, self.ptype):
                expected = (" or ".join(t.__name__ for t in self.ptype)
                            if isinstance(self.ptype, tuple) else self.ptype.__name__)
                raise TypeError(
                    f"Param '{self.name}' expects {expected}, "
                    f"got {type(value).__name__}: {value!r}"
                )
        if self.validator is not None and not self.validator(value):
            raise ValueError(f"Param '{self.name}' failed validation with value {value!r}")

    def coerce(self, value: Any) -> Any:
        if (value is not None and self.ptype is float
                and isinstance(value, int) and not isinstance(value, bool)):
            return float(value)
        return value

    def __repr__(self) -> str:
        return f"Param({self.name!r}, default={self.default!r})"


class ComplexParam(Param):
    """Param whose value is a non-JSON object (arrays, models, callables, DataFrames).

    Persisted out-of-band by the serializer (see core/serialize.py), matching the
    reference's ComplexParam + org/apache/spark/ml/Serializer.scala:1-203 design where
    each complex param saves to its own subdirectory.
    """

    def __init__(self, name: str, doc: str = "", default: Any = None,
                 validator: Optional[Callable[[Any], bool]] = None):
        super().__init__(name, doc, default, validator, ptype=None)
        self.is_complex = True


class ServiceParam(Param):
    """Value-or-column param (reference cognitive/CognitiveServiceBase.scala:29-151).

    Holds either a literal value applied to every row, or the name of an input column
    supplying a per-row value. Stored as {"value": v} or {"col": name}.
    """

    def __init__(self, name: str, doc: str = "", default: Any = None,
                 validator: Optional[Callable[[Any], bool]] = None,
                 ptype: Optional[type] = None):
        super().__init__(name, doc, default, None, ptype=None)
        self._inner_validator = validator
        self._inner_ptype = ptype
        self.is_service = True

    def validate(self, value: Any) -> None:
        if value is None:
            return
        if not (isinstance(value, dict) and (set(value) <= {"value", "col"}) and len(value) == 1):
            raise TypeError(
                f"ServiceParam '{self.name}' expects {{'value': v}} or "
                f"{{'col': name}}, got {value!r}"
            )
        if "col" in value and not isinstance(value["col"], str):
            raise TypeError(f"ServiceParam '{self.name}' column name must be str")


class Params:
    """Base for anything carrying params (stages, models, evaluators).

    Param declaration is class-level: subclasses declare ``Param`` instances as class
    attributes. Instance values live in ``self._param_map``; lookup order is instance
    value -> declared default (same two-level scheme as SparkML paramMap/defaultParamMap).
    """

    def __init__(self, **kwargs: Any):
        self._param_map: Dict[str, Any] = {}
        self.set_params(**kwargs)

    # -- param discovery -------------------------------------------------
    @classmethod
    def params(cls) -> Dict[str, Param]:
        out: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[v.name] = v
        return out

    @classmethod
    def param(cls, name: str) -> Param:
        p = cls.params().get(name)
        if p is None:
            raise KeyError(f"{cls.__name__} has no param '{name}'")
        return p

    @classmethod
    def has_param(cls, name: str) -> bool:
        return name in cls.params()

    # -- get/set ---------------------------------------------------------
    def set(self, name: str, value: Any) -> "Params":
        p = self.param(name)
        p.validate(value)
        self._param_map[name] = p.coerce(value)
        return self

    def set_params(self, **kwargs: Any) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    def get(self, name: str) -> Any:
        if name in self._param_map:
            return self._param_map[name]
        return self.param(name).default

    def get_or_throw(self, name: str) -> Any:
        v = self.get(name)
        if v is None:
            raise ValueError(f"Param '{name}' is required but not set on {type(self).__name__}")
        return v

    def is_set(self, name: str) -> bool:
        return name in self._param_map

    def is_defined(self, name: str) -> bool:
        return self.is_set(name) or self.param(name).default is not None

    def clear(self, name: str) -> "Params":
        self._param_map.pop(name, None)
        return self

    # -- service param helpers (value-or-column) ------------------------
    def set_scalar(self, name: str, value: Any) -> "Params":
        """Set a ServiceParam to a literal value."""
        return self.set(name, {"value": value})

    def set_col(self, name: str, col: str) -> "Params":
        """Set a ServiceParam to read from a column."""
        return self.set(name, {"col": col})

    def get_service_value(self, name: str, partition: Dict[str, Any], i: int) -> Any:
        """Resolve a ServiceParam for row ``i`` of a partition."""
        v = self.get(name)
        if v is None:
            return None
        if "value" in v:
            return v["value"]
        return partition[v["col"]][i]

    # -- introspection ---------------------------------------------------
    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self.params().items()):
            cur = self._param_map.get(name, p.default)
            lines.append(f"{name}: {p.doc} (default: {p.default!r}, current: {cur!r})")
        return "\n".join(lines)

    def extract_param_map(self) -> Dict[str, Any]:
        out = {name: p.default for name, p in self.params().items()}
        out.update(self._param_map)
        return out

    def simple_params(self) -> Dict[str, Any]:
        """Set (non-default) JSON-serializable params, for persistence."""
        cls_params = self.params()
        return {
            k: v for k, v in self._param_map.items()
            if not cls_params[k].is_complex
        }

    def complex_params(self) -> Dict[str, Any]:
        cls_params = self.params()
        return {
            k: v for k, v in self._param_map.items()
            if cls_params[k].is_complex
        }

    def copy(self, extra: Optional[Dict[str, Any]] = None) -> "Params":
        new = _copy.copy(self)
        new._param_map = dict(self._param_map)
        if extra:
            for k, v in extra.items():
                new.set(k, v)
        return new

    def _fluent(self) -> "Params":
        return self

    def __repr__(self) -> str:
        shown = ", ".join(f"{k}={v!r}" for k, v in sorted(self._param_map.items())
                          if not isinstance(v, (bytes, bytearray)))
        return f"{type(self).__name__}({shown})"


def _make_setter(pname: str):
    def setter(self, value):
        return self.set(pname, value)
    return setter


def _mixin(param_name: str, doc: str, default: Any = None, ptype: Optional[type] = None,
           validator=None) -> type:
    """Build a Has<X>Col-style mixin class (reference core/contracts/Params.scala:9-177)."""
    p = Param(param_name, doc, default, validator, ptype)
    ns = {
        param_name: p,
        f"set_{_snake(param_name)}": _make_setter(param_name),
        f"get_{_snake(param_name)}": (lambda self, _n=param_name: self.get(_n)),
    }
    return type(f"Has{param_name[0].upper()}{param_name[1:]}", (Params,), ns)


def _snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


# Shared column-param mixins, mirroring the reference's contracts
# (core/contracts/Params.scala:9-177).
HasInputCol = _mixin("inputCol", "The name of the input column", None, str)
HasOutputCol = _mixin("outputCol", "The name of the output column", None, str)
HasInputCols = _mixin("inputCols", "The names of the input columns", None, (list, tuple))
HasOutputCols = _mixin("outputCols", "The names of the output columns", None, (list, tuple))
HasLabelCol = _mixin("labelCol", "The name of the label column", "label", str)
HasFeaturesCol = _mixin("featuresCol", "The name of the features column", "features", str)
HasWeightCol = _mixin("weightCol", "The name of the weight column", None, str)
HasScoresCol = _mixin("scoresCol", "The name of the scores column", "scores", str)
HasScoredLabelsCol = _mixin(
    "scoredLabelsCol", "The name of the scored-labels column", "scored_labels", str)
HasScoredProbabilitiesCol = _mixin(
    "scoredProbabilitiesCol", "The name of the scored-probabilities column",
    "scored_probabilities", str)
HasEvaluationMetric = _mixin("evaluationMetric", "Metric to evaluate models with", None, str)
HasValidationIndicatorCol = _mixin(
    "validationIndicatorCol", "Boolean column marking validation rows", None, str)
HasInitScoreCol = _mixin("initScoreCol", "Column with initial model scores", None, str)
HasGroupCol = _mixin("groupCol", "Group/query id column (ranking)", None, str)
HasBatchSize = _mixin("batchSize", "Rows per minibatch", 32, int, lambda v: v > 0)
HasSeed = _mixin("seed", "Random seed", 0, int)
HasParallelism = _mixin("parallelism", "Max concurrent evaluations", 1, int, lambda v: v > 0)
HasHandleInvalid = _mixin(
    "handleInvalid", "Strategy for invalid entries: 'error', 'skip', or 'keep'", "error", str,
    lambda v: v in ("error", "skip", "keep"))
