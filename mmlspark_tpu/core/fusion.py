"""Device-resident pipeline fusion: adjacent stages -> one XLA program.

``PipelineModel.transform`` executes stage-by-stage through host numpy:
every boundary between two device-capable stages pays a D2H readback, a
host re-batching pass, and a fresh H2D upload. This module removes those
boundaries (the TVM argument, arXiv:1802.04799, applied to SparkML-style
Transformer chains):

  - ``plan(stages, schema)`` partitions a fitted stage list into maximal
    runs of device-capable stages (``stage.device_fn(schema)`` — see
    core/device_stage.py) plus host stages. A host-only stage splits a
    segment; a ``terminal`` device stage (one whose outputs finalize on
    host, e.g. GBDT's f64 objective transforms) ends one.
  - ``Segment`` composes its stages' device fns into ONE jittable program:
    batches stack once, ride the TransferRing (parallel/ingest.py — uint8
    wire in, H2D on the prefetch thread, one dispatch, one readback), and
    every executable is cached in the shared CompileCache keyed by
    (segment, bucketed batch shape, dtype).
  - ``FusedPipelineModel`` is the drop-in runner ``PipelineModel.fuse()``
    returns. Fused output is BITWISE-IDENTICAL to the unfused chain: device
    fns carry only provably-exact ops; anything host-flavored runs in the
    stages' prepare/finalize hooks using the unfused code paths, and any
    partition the contract cannot hold for (ragged rows, sparse rows,
    nulls into NaN-filling stages, unsupported dtypes) falls back to the
    host path per partition — never a wrong answer, never a failure.

Batch bucketing mirrors parallel/batching.py (power-of-two buckets) so a
segment compiles O(log n) shapes; `fusion_stats()` exposes the segment
layout, per-segment ingest decomposition, compile-cache hit rate, and any
fallbacks taken.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import profiling
from .dataframe import DataFrame
from .device_stage import CompileCache, DeviceFn, FusionUnsupported, compile_cache
from .pipeline import PipelineModel, Transformer
from .schema import Schema


class _HostFallback(Exception):
    """Internal: this partition (or segment) must run the unfused path."""


# ---------------------------------------------------------------------------
# Planning
# ---------------------------------------------------------------------------


class HostStage:
    """Plan node: a stage executed through its normal transform()."""

    __slots__ = ("stage",)

    def __init__(self, stage: Transformer):
        self.stage = stage

    @property
    def label(self) -> str:
        return type(self.stage).__name__

    def describe(self) -> Dict[str, Any]:
        return {"kind": "host", "stages": [self.label]}


class Segment:
    """Plan node: a maximal run of device-capable stages fused into one
    compiled program per (batch shape, dtype) signature."""

    def __init__(self):
        self.stages: List[Transformer] = []
        self.dfns: List[DeviceFn] = []
        # stage names the plan kept a segment OPEN across: their terminal
        # host finalize is transpiled (DeviceFn.device_finalize) so the
        # boundary they would force disappears (the compiler-search stitch,
        # docs/compiler_search.md); empty for every plan produced without
        # the stitch knob
        self.stitched: List[str] = []
        # out_cols of stitched stages: they materialize only on HOST at
        # finalize time, so no later in-segment stage may consume them
        self.host_cols: set = set()

    # -- construction ----------------------------------------------------
    def add(self, stage: Transformer, dfn: DeviceFn) -> None:
        self.stages.append(stage)
        self.dfns.append(dfn)

    def mark_stitched(self, stage: Transformer, dfn: DeviceFn) -> None:
        """Record that the segment continues PAST this terminal stage: its
        f64 host-finalize reductions are transpiled to a device shim
        (``device_finalize``), so downstream device stages keep consuming
        the segment's device-resident columns instead of paying the
        readback + ``rows_to_batch`` re-batch + H2D round-trip a segment
        break costs. The stage's finalized output columns stay host-only
        (``host_cols``) — a later stage reading them still splits."""
        self.stitched.append(type(stage).__name__)
        self.host_cols |= set(dfn.out_cols)

    def can_accept(self, dfn: DeviceFn) -> bool:
        if not self.dfns:
            return True
        # a stitched terminal stage's columns exist only on host: a reader
        # cannot join the device program
        if set(dfn.in_cols) & self.host_cols:
            return False
        written = self.written_cols - self.host_cols
        internal_in = set(dfn.in_cols) & written
        if internal_in and not dfn.internal_ok:
            return False
        # a prepare hook may only own external inputs no earlier stage reads
        if dfn.prepare is not None:
            earlier_ext = {c for d in self.dfns for c in d.in_cols
                           if c not in written and c not in self.host_cols}
            if set(dfn.in_cols) & earlier_ext:
                return False
        return True

    # -- derived layout --------------------------------------------------
    @property
    def written_cols(self) -> set:
        return {c for d in self.dfns for c in d.out_cols}

    @property
    def external_in_cols(self) -> List[str]:
        ext: List[str] = []
        written: set = set()
        for d in self.dfns:
            for c in d.in_cols:
                if c not in written and c not in ext:
                    ext.append(c)
            written |= set(d.out_cols)
        return ext

    @property
    def key(self) -> Tuple:
        return tuple(d.key for d in self.dfns)

    @property
    def label(self) -> str:
        return "+".join(type(s).__name__ for s in self.stages)

    @property
    def heavy(self) -> bool:
        return any(d.heavy for d in self.dfns)

    def readback_plan(self, transpiled: Tuple[int, ...] = ()
                      ) -> List[Tuple[str, int]]:
        """(env key, writer dfn index) pairs the executor reads back: each
        column at its FINAL value plus every internal ``__`` key — plus,
        for dfn indices in ``transpiled``, the extra outputs their
        ``device_finalize`` computes on device."""
        final_writer: Dict[str, int] = {}
        for i, d in enumerate(self.dfns):
            for c in d.out_cols:
                final_writer[c] = i
        out: List[Tuple[str, int]] = []
        for i, d in enumerate(self.dfns):
            for k in d.device_outputs:
                if k.startswith("__") or final_writer.get(k) == i:
                    out.append((k, i))
            if i in transpiled:
                out.extend((k, i) for k in d.device_finalize_outputs)
        return out

    def batch_size(self) -> int:
        for s in self.stages:
            if s.has_param("batchSize") and s.get("batchSize"):
                return int(s.get("batchSize"))
        return 256

    def ring_depth(self) -> int:
        for s in self.stages:
            if s.has_param("ringDepth") and s.get("ringDepth"):
                return int(s.get("ringDepth"))
        return 2

    def describe(self) -> Dict[str, Any]:
        out = {"kind": "fused",
               "stages": [type(s).__name__ for s in self.stages],
               "in_cols": self.external_in_cols,
               "out_cols": sorted(self.written_cols),
               "batch_size": self.batch_size()}
        if self.stitched:  # key absent on unstitched plans: describe parity
            out["stitched"] = list(self.stitched)
        return out


def plan(stages: Sequence[Transformer], schema: Schema,
         cost_model=None,
         fuse_overrides: Optional[Dict[str, bool]] = None,
         stitch_overrides: Optional[Dict[str, bool]] = None) -> List[Any]:
    """Partition a fitted stage chain into HostStage / Segment plan nodes.

    Walks the chain threading the schema through ``transform_schema``; each
    stage offers a DeviceFn via ``device_fn(schema)`` (None = host-only).
    Segments that carry no heavy stage are demoted to host stages — a
    device round-trip for column plumbing alone is a loss.

    ``cost_model`` (core/costmodel.py SegmentCostModel) upgrades that
    demotion heuristic to a PREDICTED fuse-vs-host comparison:
    ``fuse_decision(label)`` returning True keeps a light segment fused,
    False demotes it, None (uncalibrated / no host measurements) falls back
    to the heuristic — so plans from an uncalibrated model are
    bitwise-identical to the default. ``fuse_overrides`` ({label: bool},
    the Tuner's applied knob — also how its calibration probe force-fuses
    a light candidate to measure its device cost) wins over both.

    ``stitch_overrides`` ({terminal stage class name: bool}) is the
    compiler-search stitch knob: a ``terminal`` stage normally CLOSES its
    segment — its finalize runs f64 host reductions whose outputs nothing
    downstream can consume on device, so the next device stage pays a
    readback + ``rows_to_batch`` host re-batch + H2D round-trip. When the
    stage declares the transpiled shim (``stitchable`` +
    ``device_finalize``/``finalize_stitched``) and its override is True —
    or, with no override, ``cost_model.stitch_decision(segment label,
    stage name)`` prices the merge as beating the measured round-trip it
    removes (None while uncalibrated: cold-start plans stay
    bitwise-identical) — the segment stays OPEN across the shim:
    downstream device stages keep consuming the segment's device-resident
    columns, while the stage's own finalized columns stay host-only (a
    later reader of those still splits). Every per-partition host
    fallback gate is unchanged either way.
    """
    nodes: List[Any] = []
    cur: Optional[Segment] = None

    def stitch_across(seg: Segment, stage: Transformer,
                      dfn: DeviceFn) -> bool:
        if not (dfn.stitchable and dfn.device_finalize is not None
                and dfn.finalize_stitched is not None):
            return False
        name = type(stage).__name__
        if stitch_overrides is not None and name in stitch_overrides:
            return bool(stitch_overrides[name])
        if cost_model is not None:
            try:
                decision = cost_model.stitch_decision(seg.label, name)
            except Exception:  # defensive: a model bug must not kill plan
                decision = None
            return bool(decision)
        return False

    def keep_fused(seg: Segment) -> bool:
        if fuse_overrides is not None and seg.label in fuse_overrides:
            return bool(fuse_overrides[seg.label])
        if seg.heavy:
            return True
        if cost_model is not None:
            try:
                decision = cost_model.fuse_decision(seg.label)
            except Exception:  # defensive: a model bug must not kill plan
                decision = None
            if decision is not None:
                return decision
        return False

    def close():
        nonlocal cur
        if cur is not None:
            if keep_fused(cur):
                nodes.append(cur)
            else:
                nodes.extend(HostStage(s) for s in cur.stages)
            cur = None

    for stage in stages:
        dfn: Optional[DeviceFn] = None
        try:
            dfn = stage.device_fn(schema)
        except FusionUnsupported:
            dfn = None
        except Exception:  # defensive: a probing failure must not kill transform
            dfn = None
        if dfn is None:
            close()
            nodes.append(HostStage(stage))
        else:
            if cur is not None and not cur.can_accept(dfn):
                close()
            if cur is None:
                cur = Segment()
            cur.add(stage, dfn)
            if dfn.terminal:
                if stitch_across(cur, stage, dfn):
                    # transpiled shim: the segment stays open — downstream
                    # device stages keep riding this device program
                    cur.mark_stitched(stage, dfn)
                else:
                    close()
        try:
            schema = stage.transform_schema(schema.copy())
        except Exception:
            schema = schema  # schema-opaque stage: keep going with what we have
    close()
    return nodes


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------


def _stack_col(col: np.ndarray, allow_sparse: bool, stats=None) -> np.ndarray:
    """Valid-subset column -> dense [n, ...] array, preserving the wire
    dtype (uint8 pixels stay uint8); f64/i64 narrow exactly like the
    unfused Minibatcher's stack_rows(float32)/device ingestion do. A
    densified sparse column books its waste into ``stats``
    (``IngestStats.note_densify``): the dense bytes materialized vs the
    CSR bytes the same rows actually hold."""
    from ..parallel.batching import densify_sparse, is_sparse_row, sparse_width

    if col.dtype != object:
        arr = np.asarray(col)
    else:
        probe = next((v for v in col if v is not None), None)
        if probe is None:
            arr = np.zeros((len(col), 0), dtype=np.float32)
        elif is_sparse_row(probe):
            if not allow_sparse:
                raise _HostFallback("sparse rows")
            width = sparse_width(col)
            if width > (1 << 22):
                raise _HostFallback(f"sparse width {width} too large")
            arr = densify_sparse(col, width, dtype=np.float32)
            if stats is not None:
                nnz = sum(len(np.atleast_1d(v["values"]))
                          for v in col if v is not None)
                # CSR bytes: f32 values + i32 indices per nnz, i32 indptr
                nnz_bytes = nnz * 8 + (len(col) + 1) * 4
                stats.note_densify(arr.nbytes, nnz_bytes)
        else:
            rows = [np.asarray(v) for v in col]
            shapes = {r.shape for r in rows}
            if len(shapes) > 1:
                raise _HostFallback(f"ragged rows {sorted(shapes)}")
            arr = np.stack(rows)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    elif arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    elif arr.dtype == object:
        raise _HostFallback("non-array object rows")
    return np.ascontiguousarray(arr)


def _probe_info(col: np.ndarray) -> Dict[str, Any]:
    """Classify one column for the runtime dtype gates. Scans EVERY
    non-null row for sparseness — a partition whose first row is dense but
    a later row sparse (or vice versa) must read as ``mixed`` and take the
    clean host fallback, not mis-classify off row 0 and crash the stack."""
    from ..parallel.batching import is_sparse_row

    if col.dtype != object:
        return {"dtype": col.dtype, "ndim": col.ndim - 1, "sparse": False,
                "mixed": False}
    probe = None
    n_sparse = n_rows = 0
    for v in col:
        if v is None:
            continue
        if probe is None:
            probe = v
        n_rows += 1
        if is_sparse_row(v):
            n_sparse += 1
    if probe is None:
        return {"dtype": None, "ndim": None, "sparse": False, "mixed": False}
    if n_sparse:
        return {"dtype": np.dtype(np.float32), "ndim": 1, "sparse": True,
                "mixed": n_sparse != n_rows}
    arr = np.asarray(probe)
    return {"dtype": arr.dtype, "ndim": arr.ndim, "sparse": False,
            "mixed": False}


def _csr_from_rows(col: np.ndarray, width: int
                   ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Sparse object column -> (indptr i32 [n+1], indices i32 [nnz],
    values f32 [nnz]). Semantics match ``densify_sparse`` exactly so the
    CSR path stays bitwise-equal to the densify path: indices >= width
    drop (VW masking), duplicate indices keep the LAST value (numpy fancy
    assignment), explicit zeros stay (they densify to the 0.0 fill), and
    per-row indices sort ascending (the gather kernel's key order). None
    = ineligible (a negative index — only hostile producers emit those;
    the caller densifies instead)."""
    indptr = np.zeros(len(col) + 1, dtype=np.int32)
    idx_parts: List[np.ndarray] = []
    val_parts: List[np.ndarray] = []
    for i, v in enumerate(col):
        if v is None:
            indptr[i + 1] = indptr[i]
            continue
        idx = np.atleast_1d(np.asarray(v["indices"], dtype=np.int64))
        vals = np.atleast_1d(np.asarray(v["values"], dtype=np.float32))
        if idx.size and int(idx.min()) < 0:
            return None
        keep = idx < width
        idx, vals = idx[keep], vals[keep]
        order = np.argsort(idx, kind="stable")
        idx, vals = idx[order], vals[order]
        if idx.size > 1:
            last = np.ones(idx.size, dtype=bool)
            last[:-1] = idx[1:] != idx[:-1]
            idx, vals = idx[last], vals[last]
        idx_parts.append(idx.astype(np.int32))
        val_parts.append(vals)
        indptr[i + 1] = indptr[i] + idx.size
    indices = np.concatenate(idx_parts) if idx_parts \
        else np.zeros(0, dtype=np.int32)
    values = np.concatenate(val_parts) if val_parts \
        else np.zeros(0, dtype=np.float32)
    return indptr, indices, values


def _default_finalize(outs: Dict[str, np.ndarray], ctx: Dict) -> Dict[str, np.ndarray]:
    """Readback arrays -> partition columns: 1-D stays a numeric column,
    [n, ...] becomes an object column of per-row views (DNN output parity)."""
    cols: Dict[str, np.ndarray] = {}
    for name, arr in outs.items():
        if arr.ndim <= 1:
            cols[name] = arr
        else:
            obj = np.empty(len(arr), dtype=object)
            for i in range(len(arr)):
                obj[i] = arr[i]
            cols[name] = obj
    return cols


class SegmentExecutor:
    """Runs one Segment over a DataFrame, partition by partition, through
    the TransferRing with compile-cache-backed fused executables."""

    def __init__(self, segment: Segment, cache: Optional[CompileCache] = None,
                 buckets: Optional[Tuple[int, ...]] = None,
                 cost_model=None, slot_pool=None, mega_k: int = 1,
                 sharding=None, kernel_variants=None, stitch=None,
                 layout: Optional[str] = None):
        self.segment = segment
        self.cache = cache if cache is not None else compile_cache()
        self.fallbacks: List[str] = []
        # cost-aware bucket SET for short batches (auto-tuner knob; None =
        # the power-of-two default — bitwise-identical cold start)
        self.buckets = tuple(sorted(buckets)) if buckets else None
        # cost model fed by host-fallback timings (the fuse-vs-host term)
        self.cost_model = cost_model
        # pre-allocated H2D staging slots (parallel/ingest.py SlotPool);
        # None = the legacy allocating path, bitwise-identical
        self.slot_pool = slot_pool
        # K-step mega-dispatch factor for the submit path (auto-tuner knob,
        # core/costmodel.py choose_mega_k); 1 = today's per-batch dispatch
        self.mega_k = max(1, int(mega_k or 1))
        # mesh sharding (parallel/shardplan.py SegmentSharding, auto-tuner
        # knob via costmodel.choose_sharding); None = the single-device
        # path, byte-for-byte today's code
        self.sharding = sharding
        # compiler-search knobs (docs/compiler_search.md), both default OFF:
        # kernel_variants maps this segment's shape bucket (or "*") to a
        # core/kernels.py variant id activated around the trace, and stitch
        # ({stage class name: bool}) enables each stage's transpiled
        # `device_finalize` in place of the host `finalize` numeric path
        kv: Dict[Any, str] = {}
        for k, v in (kernel_variants or {}).items():
            if not v:
                continue
            try:
                kv[int(k)] = str(v)
            except (TypeError, ValueError):
                kv["*"] = str(v)
        self.kernel_variants = kv
        self.stitch = {str(k): bool(v) for k, v in (stitch or {}).items()}
        # sparse layout knob (auto-tuner via costmodel.choose_layout):
        # "csr" stages capable sparse columns as (indptr, indices, values)
        # triples instead of densifying; None = the densify path, byte-
        # for-byte today's code (docs/sparse.md)
        self.layout = str(layout) if layout else None
        # transpiled finalizers: every stage the PLAN stitched the segment
        # across, plus any stage the stitch knob names directly (a terminal
        # segment tail with no downstream to merge — the transpile alone
        # still moves its f64 reductions onto the device program)
        self._transpiled: Tuple[int, ...] = tuple(
            i for i, (s, d) in enumerate(zip(segment.stages, segment.dfns))
            if d.device_finalize is not None
            and d.finalize_stitched is not None
            and (type(s).__name__ in segment.stitched
                 or self.stitch.get(type(s).__name__)))
        # `stitch=` shape prefix: transpiled-shim programs decorate their
        # cost records so bucket_of_shape skips them (costmodel.py), like
        # mega{k};/spec=
        names = tuple(dict.fromkeys(
            type(segment.stages[i]).__name__ for i in self._transpiled))
        self._stitch_pre = f"stitch={','.join(names)};" if names else ""
        # the transpiled program differs under the SAME seg.key: key apart
        self._stitch_tail: Tuple = \
            (("stitch", self._transpiled),) if self._transpiled else ()

    def _cost_attrs(self) -> Dict[str, Any]:
        """XLA cost attrs for this segment's trace spans (mean per-batch
        flops/bytes across compiled shape buckets; empty when the backend
        reported none) — a traced p99 spike carries its cost context."""
        cost = self.cache.segment_cost(self.segment.label)
        if not cost:
            return {}
        out: Dict[str, Any] = {}
        for k in ("flops", "bytes_accessed", "peak_memory_bytes"):
            if k in cost:
                out[k] = round(cost[k], 1)
        return out

    # -- host path -------------------------------------------------------
    def _host_partition(self, part: Dict[str, np.ndarray], schema: Schema
                        ) -> List[Dict[str, np.ndarray]]:
        sub = DataFrame([dict(part)], schema.copy())
        n = len(next(iter(part.values()))) if part else 0
        for s in self.segment.stages:
            t0 = time.perf_counter()
            sub = s.transform(sub)
            if self.cost_model is not None and n > 0:
                # the measured HOST side of the fuse-vs-host comparison
                self.cost_model.observe_host(
                    type(s).__name__, time.perf_counter() - t0, n)
        return sub.partitions

    def _put_params(self, jax):
        """Stage-params placement: replicated over the mesh when sharded,
        the plain single-device put (today's code, verbatim) otherwise."""
        params = tuple(d.params for d in self.segment.dfns)
        if self.sharding is None:
            return jax.device_put(params)
        return self.sharding.put_params(params)

    # -- fused path ------------------------------------------------------
    def run(self, df: DataFrame, stats) -> DataFrame:
        import jax

        from ..obs.trace import current_batch

        seg = self.segment
        params_dev = self._put_params(jax)
        obs = current_batch()  # serving batch's trace binding (or None)
        t_wall, t0 = time.time(), time.perf_counter()
        out_parts: List[Dict[str, np.ndarray]] = []
        for part in df.partitions:
            try:
                out_parts.append(
                    self._run_partition(dict(part), params_dev, stats))
            except _HostFallback as e:
                self.fallbacks.append(f"{seg.label}: {e}")
                out_parts.extend(self._host_partition(part, df.schema))
        if obs is not None:
            tracer, ctxs = obs
            tracer.record_batch(f"segment:{seg.label}", ctxs, t_wall,
                                time.perf_counter() - t0,
                                **self._cost_attrs())
        return self._overlay(df, out_parts)

    def _overlay(self, df: DataFrame, out_parts: List[Dict[str, np.ndarray]]
                 ) -> DataFrame:
        """Overlay the chained stage schema onto the produced partitions,
        inferring any column a stage's transform_schema didn't declare."""
        chained = df.schema.copy()
        for s in self.segment.stages:
            try:
                chained = s.transform_schema(chained)
            except Exception:
                pass
        inferred = DataFrame(out_parts)
        types = {name: chained.types.get(name, inferred.schema.types[name])
                 for name in inferred.schema.names}
        meta = {k: v for k, v in chained.metadata.items() if k in types}
        return DataFrame(out_parts, Schema(types, meta))

    def _prep_partition(self, part: Dict[str, np.ndarray],
                        stats=None) -> Dict[str, Any]:
        """Host-side prep for one partition — validity masks, per-stage
        prepare hooks, dtype/sparse/null gates, dense stacking — everything
        up to (but excluding) device dispatch. Raises _HostFallback when the
        fused contract cannot hold; returns the execution state shared by
        the blocking ring path (``_run_partition``) and the non-blocking
        submit path (``submit_run``)."""
        seg = self.segment
        ext = seg.external_in_cols
        for c in ext:
            if c not in part:
                raise _HostFallback(f"missing column {c!r}")
        n = len(part[ext[0]]) if ext else 0

        # nulls into a NaN-filling stage cannot propagate-as-null: host path
        for dfn in seg.dfns:
            if dfn.null_policy != "fallback":
                continue
            for c in dfn.in_cols:
                if c in ext and part[c].dtype == object and \
                        any(v is None for v in part[c]):
                    raise _HostFallback(f"nulls in {c!r}")

        valid = np.ones(n, dtype=bool)
        for c in ext:
            col = part[c]
            if col.dtype == object:
                valid &= np.array([v is not None for v in col], dtype=bool)
        sub = {c: part[c][valid] for c in ext}
        ctx: Dict[str, Any] = {}

        # host prep (segment-external inputs only): the unfused per-row
        # code. A column an EARLIER in-segment stage writes is internal to
        # this stage even when it shares the external column's name — its
        # value arrives device-resident, so prepare must not touch it.
        written: set = set()
        for dfn in seg.dfns:
            if dfn.prepare is not None:
                mine = {c: sub[c] for c in dfn.in_cols
                        if c in sub and c not in written}
                if mine:
                    sub.update(dfn.prepare(mine, ctx))
            written |= set(dfn.out_cols)
        # prep can null rows (decode failures): shrink validity like dropNa
        n_valid = int(valid.sum())
        if n_valid:
            keep = np.ones(n_valid, dtype=bool)
            for c in ext:
                col = sub[c]
                if col.dtype == object:
                    keep &= np.array([v is not None for v in col], dtype=bool)
            if not keep.all():
                sub = {c: v[keep] for c, v in sub.items()}
                for k, v in list(ctx.items()):
                    if isinstance(v, np.ndarray) and len(v) == n_valid:
                        ctx[k] = v[keep]
                idx = np.flatnonzero(valid)
                valid = np.zeros(n, dtype=bool)
                valid[idx[keep]] = True
                n_valid = int(valid.sum())

        # runtime dtype gates. A mixed sparse/dense column (first row dense,
        # later rows sparse or vice versa) can satisfy no stacking contract:
        # clean host fallback instead of a mis-classified crash downstream.
        probes = {c: _probe_info(sub[c]) for c in ext}
        mixed = sorted(c for c, p in probes.items() if p.get("mixed"))
        if mixed:
            raise _HostFallback(f"mixed sparse/dense rows in {mixed}")
        csr_cols = self._csr_capable(probes)
        # density term (costmodel.observe_nnz): fed for EVERY sparse
        # external column — including ones about to take the reject_sparse
        # host fallback — so choose_layout can calibrate while the layout
        # knob is still off. Observation only; outputs are untouched.
        if self.cost_model is not None and n_valid > 0:
            from ..parallel.batching import sparse_width

            for c in ext:
                if probes[c]["sparse"]:
                    nnz = sum(len(np.atleast_1d(v["values"]))
                              for v in sub[c] if v is not None)
                    self.cost_model.observe_nnz(
                        seg.label, n_valid, nnz, sparse_width(sub[c]))
        for dfn, stage in zip(seg.dfns, seg.stages):
            mine = {c: probes[c] for c in dfn.in_cols if c in probes}
            if mine and dfn.reject_sparse and any(
                    p["sparse"] for c2, p in mine.items()
                    if c2 not in csr_cols):
                raise _HostFallback("sparse rows")
            if mine and dfn.accepts is not None and not dfn.accepts(mine):
                raise _HostFallback(f"{type(stage).__name__} dtype gate")

        readback = seg.readback_plan(self._transpiled)
        state: Dict[str, Any] = {
            "part": part, "sub": sub, "ctx": ctx, "valid": valid, "n": n,
            "n_valid": n_valid, "ext": ext, "staged_cols": list(ext),
            "readback": readback, "keys": [k for k, _ in readback]}
        if n_valid > 0:
            allow_sparse = all(not d.reject_sparse for d in seg.dfns)
            dense: Dict[str, np.ndarray] = {}
            deposit: Dict[str, List[np.ndarray]] = {}
            csr: Dict[str, Tuple] = {}
            for c in ext:
                if c in csr_cols:
                    triple = self._stage_csr(sub[c], stats)
                    if triple is not None:
                        csr[c] = triple
                        continue
                    # ineligible / injected sparse.stage fault: accounted
                    # densify fallback — bitwise-equal to the dense path
                    dense[c] = _stack_col(sub[c], True, stats=stats)
                    continue
                rows = self._deposit_rows(sub[c]) \
                    if self.slot_pool is not None else None
                if rows is not None:
                    # slot-eligible: the stack deferred to _batches, which
                    # fills a pre-allocated SlotPool buffer directly (the
                    # one host copy); everything else stacks here as before
                    deposit[c] = rows
                else:
                    dense[c] = _stack_col(sub[c], allow_sparse, stats=stats)
            state["dense"] = dense
            state["deposit"] = deposit
            if csr:
                state["csr"] = csr
                staged: List[str] = []
                for c in ext:
                    if c in csr:
                        staged += [f"{c}:indptr", f"{c}:indices",
                                   f"{c}:values", f"{c}:width"]
                    else:
                        staged.append(c)
                state["staged_cols"] = staged
        return state

    def _csr_capable(self, probes: Dict[str, Dict[str, Any]]) -> set:
        """External columns eligible for CSR staging: the layout knob says
        "csr" for this segment, the column's rows are (uniformly) sparse,
        and EVERY consuming stage declares the capability
        (``DeviceFn.sparse_cols`` + ``sparse_fn``). The CSR x sharding
        combination is explicitly gated off — sharded segments keep the
        densify path (shardplan's row-split CSR spec is priced host-side
        only for now, docs/sparse.md)."""
        if self.layout != "csr" or self.sharding is not None:
            return set()
        out = set()
        for c, p in probes.items():
            if not p["sparse"] or p.get("mixed"):
                continue
            consumers = [d for d in self.segment.dfns if c in d.in_cols]
            if consumers and all(c in d.sparse_cols
                                 and d.sparse_fn is not None
                                 for d in consumers):
                out.add(c)
        return out

    def _stage_csr(self, col: np.ndarray, stats=None) -> Optional[Tuple]:
        """One sparse column -> (indptr, indices, values, width), or None
        to take the accounted densify fallback (zero-width column, an i32
        composite-key overflow, a negative index, or an injected
        ``sparse.stage`` fault)."""
        from ..parallel.batching import sparse_width

        from . import faults

        width = sparse_width(col)
        # the gather kernel's composite keys are row*width + index in i32
        if width <= 0 or self.segment.batch_size() * width >= (1 << 31):
            return None
        try:
            faults.fire(faults.SPARSE_STAGE)
        except faults.InjectedFault:
            return None
        triple = _csr_from_rows(col, width)
        if triple is None:
            return None
        indptr, indices, values = triple
        if stats is not None:
            stats.note_csr(int(indptr[-1]) * 8 + indptr.nbytes,
                           len(col) * width * 4)
        return indptr, indices, values, width

    @staticmethod
    def _deposit_rows(col: np.ndarray) -> Optional[List[np.ndarray]]:
        """Rows eligible for slot deposit: an object column of uniform,
        dense ndarray rows whose dtype ships as-is (no f64->f32 / i64->i32
        narrowing and no sparse densify — those transforms need their own
        allocation), so filling the staging slot IS the single host copy.
        Every fallback decision is made HERE, before any generator runs on
        a ring thread. None = take ``_stack_col`` (the copying path)."""
        if col.dtype != object or len(col) == 0:
            return None
        rows = list(col)
        first = rows[0]
        if not isinstance(first, np.ndarray):
            return None
        shape, dt = first.shape, first.dtype
        if dt == object or dt in (np.dtype(np.float64), np.dtype(np.int64)):
            return None
        for r in rows[1:]:
            if not isinstance(r, np.ndarray) or r.shape != shape \
                    or r.dtype != dt:
                return None
        return rows

    def _batches(self, state: Dict[str, Any], stats=None):
        """Padded/bucketed Batch stream over the partition's dense arrays.

        Deposit-eligible columns (``state["deposit"]``) fill a pre-allocated
        SlotPool buffer in place — stack + pad collapse into one copy into
        the reusable H2D staging slot; slot contention (acquire timeout)
        falls back to the allocating path with an accounted copy
        (``IngestStats.note_copy``)."""
        from ..parallel.batching import Batch, next_bucket, pad_batch
        from ..parallel.ingest import rows_to_batch

        batch_size = self.segment.batch_size()
        dense, ext = state["dense"], state["ext"]
        deposit = state.get("deposit") or {}
        csr = state.get("csr") or {}
        n_valid = state["n_valid"]
        # sharded over the mesh's data axis: every padded batch must split
        # evenly across the shards, so targets round UP to a shard multiple
        # (the pad rows are masked out at readback exactly like bucket pad)
        shards = self.sharding.shards if self.sharding is not None else 1
        for start in range(0, n_valid, batch_size):
            stop = min(start + batch_size, n_valid)
            m = stop - start
            target = batch_size if m == batch_size \
                else min(next_bucket(m, buckets=self.buckets), batch_size)
            if shards > 1:
                target = -(-target // shards) * shards
            arrays = {c: pad_batch(dense[c][start:stop], target)
                      for c in dense}
            for c, (indptr, indices, values, width) in csr.items():
                # CSR window slice: rebase the indptr to this window and pad
                # row-wise by REPEATING the last offset (pad rows are empty),
                # nnz-wise to a power-of-two bucket with zeros. Padded nnz
                # entries resolve to row `target` in the gather kernel's
                # composite-key space (key >= target*width), past every real
                # query — they can never alias a live cell. docs/sparse.md.
                base = int(indptr[start])
                nnz_b = int(indptr[stop]) - base
                ip = (indptr[start:stop + 1] - base).astype(np.int32)
                if m < target:
                    ip = np.pad(ip, (0, target - m), mode="edge")
                nnz_pad = next_bucket(max(1, nnz_b))
                idx = np.pad(np.asarray(indices[base:base + nnz_b],
                                        dtype=np.int32),
                             (0, nnz_pad - nnz_b))
                val = np.pad(np.asarray(values[base:base + nnz_b],
                                        dtype=np.float32),
                             (0, nnz_pad - nnz_b))
                arrays[f"{c}:indptr"] = ip
                arrays[f"{c}:indices"] = idx
                arrays[f"{c}:values"] = val
                arrays[f"{c}:width"] = np.asarray(width, dtype=np.int32)
            lease = None
            if deposit:
                spec = {c: ((target,) + rows[0].shape, rows[0].dtype)
                        for c, rows in deposit.items()}
                lease = self.slot_pool.acquire(spec, stats=stats) \
                    if self.slot_pool is not None else None
                if lease is not None:
                    lease.fill_begin()
                    for c, rows in deposit.items():
                        buf = lease.arrays[c]
                        rows_to_batch(rows[start:stop], out=buf,
                                      stats=stats)
                        if m < target:
                            buf[m:] = 0  # pad parity with pad_batch zeros
                        arrays[c] = buf
                    lease.fill_end()
                    if stats is not None:
                        stats.note_deposit()
                else:
                    for c, rows in deposit.items():
                        arrays[c] = pad_batch(
                            rows_to_batch(rows[start:stop], stats=stats),
                            target)
                    if stats is not None:
                        stats.note_copy()
            # analysis: allow D001 -- host-side validity mask, never shipped
            mask = np.zeros(target, dtype=bool)
            mask[:m] = True
            yield Batch(arrays, mask, m, staging=lease)

    def _put(self, batch):
        import jax

        if self.sharding is None:
            return jax.device_put(batch.arrays), batch.num_valid
        # sharded staging: each column lands pre-split across the mesh's
        # candidate axis. A failure here (a chip dropping out mid-stage —
        # the mesh.chip_wedge chaos seam) degrades this PARTITION to the
        # host fallback: slower, never wrong.
        try:
            return self.sharding.device_put(batch.arrays), batch.num_valid
        except Exception as e:  # noqa: BLE001 — any stage fault demotes
            raise FusionUnsupported(f"mesh stage failure: {e}")

    @staticmethod
    def _sig_of(x, ext) -> Tuple:
        """Shape signature of one staged input dict (CompileCache key)."""
        return tuple((c, tuple(np.shape(x[c])), str(x[c].dtype))
                     for c in ext)

    @staticmethod
    def _shape_key_of(sig) -> str:
        return ";".join(f"{c}={'x'.join(str(d) for d in shp)}:{dt}"
                        for c, shp, dt in sig)

    def _variant_for(self, sig) -> Optional[str]:
        """Kernel-variant id active for one shape signature: the tuned
        per-bucket entry (bucket = leading dim of the first staged input),
        falling back to the ``"*"`` wildcard; None = built-in default."""
        kv = self.kernel_variants
        if not kv:
            return None
        vid = None
        if sig and sig[0][1]:
            vid = kv.get(int(sig[0][1][0]))
        if vid is None:
            vid = kv.get("*")
        return vid

    def _make_step(self, params_dev, state: Dict[str, Any]):
        """Dispatch closure: staged batch -> (device outputs, num_valid).
        Non-blocking (jax dispatch is async); executables come from the
        shared CompileCache keyed by (segment, shape signature)."""
        seg, keys = self.segment, state["keys"]
        staged_cols = state.get("staged_cols") or state["ext"]
        csr_cols = frozenset(state.get("csr") or ())
        sh = self.sharding
        # a sharded executable is a DIFFERENT program (GSPMD-partitioned,
        # collectives inserted): key it apart from the single-device one,
        # and prefix the shape key so the cost model's bucket parser skips
        # sharded records (their per-chip flops would skew the
        # single-device analytic table)
        key_tail = (sh.cache_key(),) if sh is not None else ()
        key_tail = key_tail + self._stitch_tail
        shape_pre = (sh.shape_prefix() if sh is not None else "") + \
            self._stitch_pre
        if csr_cols:
            # a CSR-staged program traces sparse_fn bodies over the wire
            # triple: key it apart, and prefix the shape key so
            # bucket_of_shape skips its cost records (the nnz bucket is
            # data- not batch-shaped)
            key_tail = key_tail + (("layout", "csr"),)
            shape_pre = "layout=csr;" + shape_pre

        def step(staged):
            x, m = staged
            sig = self._sig_of(x, staged_cols)
            # a kernel variant is a DIFFERENT compiled program for the same
            # (segment, signature): key it apart, and decorate the shape
            # key (variant=<id>;) so bucket_of_shape skips its cost record
            vid = self._variant_for(sig)
            tail = key_tail + ((("variant", vid),) if vid else ())
            pre = (f"variant={vid};" if vid else "") + shape_pre
            compiled = self.cache.get(
                (seg.key, sig) + tail,
                lambda: self._build(params_dev, x, keys, variant=vid,
                                    csr_cols=csr_cols),
                label=seg.label, shape=pre + self._shape_key_of(sig))
            with profiling.annotate(f"fused:{seg.label}"):
                return compiled(params_dev, x), m

        return step

    def _make_mega_step(self, params_dev, state: Dict[str, Any], k: int):
        """K-step dispatch closure: a list of K same-signature staged
        batches -> tuple of K output tuples, through ONE compiled call.
        The shape key is prefixed so the cost model's bucket parser skips
        mega records (their flops are K batches' worth — folding them into
        a single-batch bucket would skew the analytic roofline)."""
        seg, keys = self.segment, state["keys"]
        staged_cols = state.get("staged_cols") or state["ext"]
        csr_cols = frozenset(state.get("csr") or ())
        sh = self.sharding
        key_tail = (sh.cache_key(),) if sh is not None else ()
        key_tail = key_tail + self._stitch_tail
        shape_pre = (sh.shape_prefix() if sh is not None else "") + \
            self._stitch_pre
        if csr_cols:
            key_tail = key_tail + (("layout", "csr"),)
            shape_pre = "layout=csr;" + shape_pre

        def mega(group):
            xs = [x for (x, _m), _t in group]
            sig = self._sig_of(xs[0], staged_cols)
            vid = self._variant_for(sig)
            tail = key_tail + ((("variant", vid),) if vid else ())
            pre = (f"variant={vid};" if vid else "") + shape_pre
            compiled = self.cache.get(
                (seg.key, sig, ("mega", k)) + tail,
                lambda: self._build_mega(params_dev, xs[0], keys, k,
                                         variant=vid, csr_cols=csr_cols),
                label=seg.label,
                shape=f"{pre}mega{k};{self._shape_key_of(sig)}")
            cols_seq = tuple({c: x[c] for c in staged_cols} for x in xs)
            with profiling.annotate(f"fused:{seg.label}:mega{k}"):
                return compiled(params_dev, cols_seq)

        return mega

    @staticmethod
    def _fetch(handle):
        ys, m = handle
        return tuple(np.asarray(y)[:m] for y in ys)

    def _fill_ahead(self, state: Dict[str, Any], stats):
        """Batch source for one partition: the plain generator, wrapped in
        a background fill thread when slot deposit is active — slot N+1
        fills while slot N transfers (the paired-buffer overlap; the
        SlotPool's two buffers per bucket pace the lookahead). Returns
        (iterator, closer)."""
        src = self._batches(state, stats)
        if not state.get("deposit"):
            return src, None
        from ..parallel.batching import DevicePrefetcher

        filler = DevicePrefetcher(src, depth=1)
        return iter(filler), filler

    def _run_partition(self, part: Dict[str, np.ndarray], params_dev,
                       stats) -> Dict[str, np.ndarray]:
        from ..parallel.ingest import TransferRing

        state = self._prep_partition(part, stats)
        collected: Dict[str, List[np.ndarray]] = {k: []
                                                  for k in state["keys"]}
        if state["n_valid"] > 0:
            src, filler = self._fill_ahead(state, stats)
            ring = TransferRing(src, put=self._put,
                                step=self._make_step(params_dev, state),
                                fetch=self._fetch,
                                depth=self.segment.ring_depth(), stats=stats)
            try:
                for out in ring:
                    for k, y in zip(state["keys"], out):
                        collected[k].append(y)
            except FusionUnsupported as e:
                raise _HostFallback(str(e))
            finally:
                ring.close()
                if filler is not None:
                    filler.close()
        return self._emit_partition(state, collected)

    def submit_run(self, df: DataFrame, stats):
        """Non-blocking segment execution: prep + H2D-stage + DISPATCH every
        partition's batches now, hand the device-resident handles to the
        returned zero-arg ``resolve()`` which performs readback + finalize
        (the serving executor runs it on its dedicated readback thread).
        ``resolve()`` output is bitwise-identical to ``run()``.

        Host-fallback partitions (ragged/sparse/null/dtype violations)
        execute synchronously at submit time — never a wrong answer."""
        import jax

        from ..obs.trace import current_batch
        from ..parallel.ingest import timed_stage

        seg = self.segment
        obs = current_batch()  # serving batch's trace binding (or None)
        wall0 = time.perf_counter()
        t_wall = time.time()
        params_dev = self._put_params(jax)
        mega_k = max(1, int(self.mega_k or 1))
        pendings: List[Tuple[str, Any, Any]] = []
        for part in df.partitions:
            try:
                state = self._prep_partition(dict(part), stats)
                handles = []
                if state["n_valid"] > 0:
                    step = self._make_step(params_dev, state)
                    src, filler = self._fill_ahead(state, stats)
                    try:
                        if mega_k <= 1:
                            # K=1: today's stage-then-dispatch loop,
                            # verbatim — bitwise-identical by construction
                            for batch in src:
                                staged, timing = timed_stage(
                                    self._put, batch, obs=obs)
                                td = time.perf_counter()
                                handle = step(staged)
                                timing.dispatch_s = \
                                    time.perf_counter() - td
                                handles.append((handle, timing))
                        else:
                            staged_it = (
                                timed_stage(self._put, batch, obs=obs)
                                for batch in src)
                            self._dispatch_mega(staged_it, params_dev,
                                                state, step, mega_k,
                                                handles)
                    finally:
                        if filler is not None:
                            filler.close()
                pendings.append(("device", state, handles))
            except (_HostFallback, FusionUnsupported) as e:
                self.fallbacks.append(f"{seg.label}: {e}")
                pendings.append(
                    ("host", self._host_partition(part, df.schema), None))

        def resolve() -> DataFrame:
            from ..parallel.ingest import _block_ready

            out_parts: List[Dict[str, np.ndarray]] = []
            for kind, payload, handles in pendings:
                if kind == "host":
                    out_parts.extend(payload)
                    continue
                state = payload
                collected: Dict[str, List[np.ndarray]] = {
                    k: [] for k in state["keys"]}
                for handle, timing in handles:
                    t0 = time.perf_counter()
                    _block_ready(handle)
                    t1 = time.perf_counter()
                    timing.compute_s = t1 - t0
                    out = self._fetch(handle)
                    timing.readback_s = time.perf_counter() - t1
                    stats.record(timing)
                    for k, y in zip(state["keys"], out):
                        collected[k].append(y)
                out_parts.append(self._emit_partition(state, collected))
            stats.add_wall(time.perf_counter() - wall0)
            if obs is not None:
                tracer, ctxs = obs
                tracer.record_batch(f"segment:{seg.label}", ctxs, t_wall,
                                    time.perf_counter() - wall0,
                                    **self._cost_attrs())
            return self._overlay(df, out_parts)

        return resolve

    def _dispatch_mega(self, staged_it, params_dev, state: Dict[str, Any],
                       step, k: int, handles) -> None:
        """Dispatch staged batches in SLIDING K-step groups: pull from the
        (lazily staging) iterator, and the moment K consecutive
        same-signature batches are staged, run them through the compiled
        K-step program (one Python-level dispatch for K micro-batches) and
        DROP the staged-input references — at most K staged inputs are
        alive at once, matching the ring/K=1 paths' bounded in-flight
        memory instead of staging a whole partition up front. Runs shorter
        than K (signature change or end of stream) dispatch singly through
        the ordinary step — the SAME per-batch executable as K=1, so
        outputs are identical either way. The measured mega dispatch time
        is split evenly across the K timings (the amortization the
        bottleneck attribution shows), with ``timing.mega_k`` tagging the
        share so the cost model can de-amortize it."""
        ext = state.get("staged_cols") or state["ext"]
        mega = self._make_mega_step(params_dev, state, k)

        def flush(group):
            if len(group) == k:
                td = time.perf_counter()
                outs = mega(group)
                share = (time.perf_counter() - td) / k
                for (staged, timing), ys in zip(group, outs):
                    timing.dispatch_s = share
                    timing.mega_k = k
                    handles.append(((ys, staged[1]), timing))
            else:
                for staged, timing in group:
                    td = time.perf_counter()
                    handle = step(staged)
                    timing.dispatch_s = time.perf_counter() - td
                    handles.append((handle, timing))

        group: List[Any] = []
        sig0 = None
        for item in staged_it:
            sig = self._sig_of(item[0][0], ext)
            if group and sig != sig0:
                flush(group)
                group = []
            if not group:
                sig0 = sig
            group.append(item)
            if len(group) == k:
                flush(group)
                group = []
        if group:
            flush(group)

    def _emit_partition(self, state: Dict[str, Any],
                        collected: Dict[str, List[np.ndarray]]
                        ) -> Dict[str, np.ndarray]:
        """Readback arrays -> finalized partition columns (per writer
        stage, scattered over the validity mask)."""
        seg = self.segment
        part, ctx = state["part"], state["ctx"]
        valid, n, n_valid = state["valid"], state["n"], state["n_valid"]
        readback = state["readback"]
        full = {k: (np.concatenate(v, axis=0) if v
                    else np.zeros((0,), dtype=np.float32))
                for k, v in collected.items()}

        # finalize per writer stage (stage order), scatter into the partition
        by_writer: Dict[int, Dict[str, np.ndarray]] = {}
        for k, i in readback:
            by_writer.setdefault(i, {})[k] = full[k]
        out_part = dict(part)
        transpiled = set(self._transpiled)
        for i, dfn in enumerate(seg.dfns):
            outs = by_writer.get(i)
            if outs is None:
                continue
            if n_valid == 0:
                cols = {c: np.empty(0, dtype=object) for c in dfn.out_cols}
            elif i in transpiled:
                # transpiled finalize: the numeric reductions already ran
                # on device (device_finalize); this host shim only shapes
                # the readbacks into columns
                cols = dfn.finalize_stitched(outs, ctx)
            elif dfn.finalize is not None:
                cols = dfn.finalize(outs, ctx)
            else:
                cols = _default_finalize(outs, ctx)
            for c in dfn.out_cols:
                if c not in cols:
                    continue
                col = cols[c]
                if n_valid == n:
                    out_part[c] = col
                else:
                    scat = np.empty(n, dtype=object)
                    scat[np.flatnonzero(valid)] = col
                    out_part[c] = scat
        if any(d.drop_invalid for d in seg.dfns) and n_valid < n:
            out_part = {k: v[valid] for k, v in out_part.items()}
        return out_part

    def _build(self, params_dev, x: Dict[str, Any], keys: List[str],
               variant: Optional[str] = None,
               csr_cols: frozenset = frozenset()):
        """AOT-compile the fused program for one shape signature. A kernel
        ``variant`` id is activated around the trace (core/kernels.py) so
        variant-aware call sites resolve it as a static parameter. A stage
        whose input column was CSR-staged (``csr_cols``) traces its
        ``sparse_fn`` body over the wire-triple env keys instead of
        ``fn`` — the only point where the two bodies diverge."""
        import jax

        from . import kernels as _kernels

        seg = self.segment
        transpiled = set(self._transpiled)

        def fused(params_tuple, cols):
            env = dict(cols)
            for i, (dfn, p) in enumerate(zip(seg.dfns, params_tuple)):
                if dfn.sparse_fn is not None and csr_cols & set(dfn.in_cols):
                    env.update(dfn.sparse_fn(p, env))
                else:
                    env.update(dfn.fn(p, env))
                if i in transpiled:
                    env.update(dfn.device_finalize(p, env))
            return tuple(env[k] for k in keys)

        # sharded: pjit with the planner's NamedShardings (replicated
        # params, per-column input specs, donated ring-staged inputs) —
        # GSPMD partitions the program and inserts the collectives
        jit_kwargs = self.sharding.jit_kwargs() \
            if self.sharding is not None else {}
        jitted = jax.jit(fused, **jit_kwargs)
        specs = {c: jax.ShapeDtypeStruct(tuple(np.shape(v)),
                                         np.asarray(v).dtype
                                         if not hasattr(v, "dtype") else v.dtype)
                 for c, v in x.items()}
        with _kernels.activate(variant):
            try:
                return jitted.lower(params_dev, specs).compile()
            except FusionUnsupported:
                raise
            except Exception:
                # AOT path unavailable on this jax: the jitted callable
                # still compiles (and caches) per shape on first dispatch
                jax.eval_shape(jitted, params_dev, specs)  # gates fire NOW
                if variant is None:
                    return jitted

                def call(p, c, _jitted=jitted, _vid=variant):
                    # first real dispatch re-traces: keep the variant live
                    with _kernels.activate(_vid):
                        return _jitted(p, c)

                return call

    def _build_mega(self, params_dev, x: Dict[str, Any], keys: List[str],
                    k: int, variant: Optional[str] = None,
                    csr_cols: frozenset = frozenset()):
        """AOT-compile the K-step mega program: K replicas of ``_build``'s
        per-batch fused body, traced over a K-tuple of same-shape input
        dicts in one callable — one Python dispatch executes K queued
        micro-batches (the fixed dispatch cost amortizes K-fold). Each
        replica's ops are exactly the per-batch program's, so per-batch
        outputs match the K=1 path."""
        import jax

        from . import kernels as _kernels

        seg = self.segment
        transpiled = set(self._transpiled)

        def fused_k(params_tuple, cols_seq):
            outs = []
            for cols in cols_seq:
                env = dict(cols)
                for i, (dfn, p) in enumerate(zip(seg.dfns, params_tuple)):
                    if dfn.sparse_fn is not None \
                            and csr_cols & set(dfn.in_cols):
                        env.update(dfn.sparse_fn(p, env))
                    else:
                        env.update(dfn.fn(p, env))
                    if i in transpiled:
                        env.update(dfn.device_finalize(p, env))
                outs.append(tuple(env[kk] for kk in keys))
            return tuple(outs)

        jit_kwargs = self.sharding.jit_kwargs(mega_k=k) \
            if self.sharding is not None else {}
        jitted = jax.jit(fused_k, **jit_kwargs)
        spec = {c: jax.ShapeDtypeStruct(
            tuple(np.shape(v)),
            np.asarray(v).dtype if not hasattr(v, "dtype") else v.dtype)
            for c, v in x.items()}
        specs = tuple(dict(spec) for _ in range(k))
        with _kernels.activate(variant):
            try:
                return jitted.lower(params_dev, specs).compile()
            except FusionUnsupported:
                raise
            except Exception:
                jax.eval_shape(jitted, params_dev, specs)
                if variant is None:
                    return jitted

                def call(p, c, _jitted=jitted, _vid=variant):
                    with _kernels.activate(_vid):
                        return _jitted(p, c)

                return call


# ---------------------------------------------------------------------------
# FusedPipelineModel
# ---------------------------------------------------------------------------


class FusedPipelineModel(PipelineModel):
    """PipelineModel whose transform executes the fused plan.

    Fusion is an EXECUTION STRATEGY, not a persisted artifact: save() writes
    a plain PipelineModel (load + ``.fuse()`` to re-fuse), and the class is
    kept out of the stage registry (``_abstract``).
    """

    _abstract = True

    def __init__(self, stages=None, cache: Optional[CompileCache] = None,
                 cost_model=None, slot_staging: bool = True, **kwargs):
        super().__init__(stages, **kwargs)
        self._cache = cache if cache is not None else compile_cache()
        self._plans: Dict[Tuple, List[Any]] = {}
        self._seg_stats: Dict[str, Any] = {}
        self._last_fallbacks: List[str] = []
        self._last_plan: Optional[List[Any]] = None
        # auto-tuning state (core/tune.py Tuner drives these): a cost model
        # feeding plan()'s fuse-vs-host comparison + host-stage timings,
        # per-segment bucket-set overrides, fuse overrides, and per-segment
        # K-step mega-dispatch factors. All default OFF: an untuned model
        # plans, buckets, and dispatches bitwise-identically.
        self._cost_model = cost_model
        self._bucket_overrides: Dict[str, Tuple[int, ...]] = {}
        self._fuse_overrides: Dict[str, bool] = {}
        self._mega_k_overrides: Dict[str, int] = {}
        # compiler-search knobs (docs/compiler_search.md): per-segment
        # {bucket: kernel variant id} and per-stage-name stitch flags.
        # Both default OFF — cold start is bitwise-identical.
        self._variant_overrides: Dict[str, Dict[Any, str]] = {}
        self._stitch_overrides: Dict[str, bool] = {}
        # pod-scale sharding (parallel/shardplan.py): the mesh segments may
        # shard over (set_mesh / MeshSupervision) and the per-segment spec
        # overrides (tuner knob via costmodel.choose_sharding). Both
        # default OFF — no mesh or no override = the single-device path.
        self._shard_mesh = None
        self._sharding_overrides: Dict[str, str] = {}
        self._seg_sharding: Dict[str, Any] = {}
        # sparse layout knob (docs/sparse.md): per-segment-label "csr"
        # stages capable sparse columns as wire triples (tuner knob via
        # costmodel.choose_layout). Default OFF — densify, bitwise today.
        self._layout_overrides: Dict[str, str] = {}
        # pipeline-parallel depth knob (parallel/pipeplan.py, tuner knob
        # via costmodel.choose_pipe_depth): > 1 places a chainable segment
        # run on disjoint pipe-axis sub-meshes and streams micro-batches
        # through them. Default OFF (None) — serial, bitwise today.
        self._pipe_depth: Optional[int] = None
        self._pipe_stats: Optional[Dict[str, Any]] = None
        self._pipe_replans = 0
        self._pipe_requeues: Dict[int, int] = {}
        self._pipe_wedge_handler = None
        self._pipe_supervision = None
        # pre-allocated H2D staging (parallel/ingest.py SlotPool), shared
        # across segments/executors; ``slot_staging=False`` pins the legacy
        # allocating path (the bench A/B arm)
        self.slot_staging = bool(slot_staging)
        self._slot_pool = None

    def fuse(self) -> "FusedPipelineModel":
        return self

    def set_tuning(self, buckets: Optional[Dict[str, Tuple[int, ...]]] = None,
                   fuse: Optional[Dict[str, bool]] = None,
                   cost_model=None,
                   mega_k: Optional[Dict[str, int]] = None,
                   sharding: Optional[Dict[str, str]] = None,
                   kernel_variants: Optional[Dict[str, Dict[Any, str]]] = None,
                   stitch: Optional[Dict[str, bool]] = None,
                   layout: Optional[Dict[str, str]] = None,
                   pipe_depth: Optional[int] = None) -> None:
        """Apply tuned knobs (Tuner.apply): per-segment-label bucket sets,
        fuse-vs-demote overrides, per-segment K-step mega-dispatch factors,
        per-segment partition-spec names (sharding over the ``set_mesh``
        mesh), per-segment kernel-variant maps ({label: {bucket|"*":
        variant id}}), per-stage-name stitch flags, the pipeline depth
        (``pipe_depth`` > 1 streams a chainable segment run over pipe-axis
        sub-meshes; <= 1 clears), and/or the cost model itself. Passing
        None leaves a knob unchanged; passing {} clears it. Cached plans
        are invalidated (compiled executables survive in the
        CompileCache)."""
        if pipe_depth is not None:
            self._pipe_depth = int(pipe_depth) \
                if int(pipe_depth) > 1 else None
        if kernel_variants is not None:
            self._variant_overrides = {
                str(k): dict(v) for k, v in kernel_variants.items() if v}
        if stitch is not None:
            self._stitch_overrides = {str(k): bool(v)
                                      for k, v in stitch.items()}
        if buckets is not None:
            self._bucket_overrides = {
                str(k): tuple(sorted(int(b) for b in v))
                for k, v in buckets.items()}
        if fuse is not None:
            self._fuse_overrides = {str(k): bool(v)
                                    for k, v in fuse.items()}
        if mega_k is not None:
            self._mega_k_overrides = {str(k): max(1, int(v))
                                      for k, v in mega_k.items()}
        if sharding is not None:
            self._sharding_overrides = {str(k): str(v)
                                        for k, v in sharding.items() if v}
        if layout is not None:
            self._layout_overrides = {str(k): str(v)
                                      for k, v in layout.items() if v}
        if cost_model is not None:
            self._cost_model = cost_model
        self._plans.clear()

    def set_mesh(self, mesh) -> None:
        """Attach (or, with None, detach) the device mesh segments may
        shard over. The mesh alone changes nothing — a segment shards only
        when a ``sharding`` override names a spec for its label (the
        tuner's journaled, rollback-able decision). MeshSupervision calls
        this with the surviving submesh after a shard-group quarantine."""
        self._shard_mesh = mesh
        self._seg_sharding.clear()
        self._plans.clear()

    @property
    def shard_mesh(self):
        return self._shard_mesh

    @property
    def cost_model(self):
        return self._cost_model

    @property
    def compile_cache(self) -> CompileCache:
        """The executable cache this model's segments compile into — the
        attachment point for the fleet's persistent tier."""
        return self._cache

    def attach_persistent_cache(self, tier, warm: bool = True
                                ) -> Dict[str, int]:
        """Fleet hook (serving/fleet/cache.py): hang the persistent tier
        under this model's CompileCache and (by default) AOT-warm —
        preload every compatible persisted executable NOW, so the first
        request for a previously-seen (segment, bucket) signature is a
        memory hit with zero jit compiles. Harvested cost records from
        the fleet's entries (including cost-only ones) feed the cost
        model, so planning starts calibrated on a fresh pod."""
        self._cache.attach_persistent(tier)
        stats = tier.warm(self._cache) if warm else \
            {"warmed": 0, "costs_only": 0, "skipped": 0, "errors": 0}
        if self._cost_model is not None:
            harvested = tier.harvested_costs()
            if harvested:
                try:
                    self._cost_model.ingest_costs(harvested)
                except Exception:  # noqa: BLE001 — warm costs best-effort
                    pass
        return stats

    @property
    def mega_k_max(self) -> int:
        """Largest active K-step dispatch factor (1 when untuned). Serving's
        DispatchWatchdog scales its budget by this so a K-batch mega-dispatch
        is not mistaken for a hang."""
        return max(self._mega_k_overrides.values(), default=1)

    def _get_slot_pool(self):
        if not self.slot_staging:
            return None
        if self._slot_pool is None:
            from ..parallel.ingest import SlotPool
            self._slot_pool = SlotPool()
        return self._slot_pool

    def _plan_for(self, schema: Schema) -> List[Any]:
        key = tuple(schema.types.items())
        if key not in self._plans:
            self._plans[key] = plan(
                self._stages, schema.copy(), cost_model=self._cost_model,
                fuse_overrides=self._fuse_overrides or None,
                stitch_overrides=self._stitch_overrides or None)
        return self._plans[key]

    def _sharding_for(self, node: Segment):
        """Resolve the segment's tuned spec name into a SegmentSharding
        (None = unsharded: no mesh, no override, 1-shard axis, or any
        resolution failure — wrong sharding must never fail a transform)."""
        name = self._sharding_overrides.get(node.label)
        if self._shard_mesh is None or not name:
            self._seg_sharding.pop(node.label, None)
            return None
        try:
            from ..parallel.shardplan import sharding_for

            sh = sharding_for(node, self._shard_mesh, name)
        except Exception:  # noqa: BLE001 — degrade to single-device
            sh = None
        if sh is None:
            self._seg_sharding.pop(node.label, None)
        else:
            self._seg_sharding[node.label] = sh.describe()
        return sh

    def _make_executor(self, node: Segment) -> SegmentExecutor:
        return SegmentExecutor(
            node, self._cache,
            buckets=self._bucket_overrides.get(node.label),
            cost_model=self._cost_model,
            slot_pool=self._get_slot_pool(),
            mega_k=self._mega_k_overrides.get(node.label, 1),
            sharding=self._sharding_for(node),
            kernel_variants=self._variant_overrides.get(node.label),
            stitch=self._stitch_overrides or None,
            layout=self._layout_overrides.get(node.label))

    def _host_node(self, node: HostStage, df: DataFrame) -> DataFrame:
        """Run one host plan node, feeding its wall time to the cost model
        (the measured host side of fuse-vs-demote) when tuning is on."""
        if self._cost_model is None:
            return node.stage.transform(df)
        n = sum(len(next(iter(p.values()))) if p else 0
                for p in df.partitions)
        t0 = time.perf_counter()
        out = node.stage.transform(df)
        if n > 0:
            self._cost_model.observe_host(
                node.label, time.perf_counter() - t0, n)
        return out

    def transform(self, df: DataFrame, fused: bool = True) -> DataFrame:
        if not fused:
            return PipelineModel.transform(self, df)
        from ..parallel.ingest import IngestStats

        nodes = self._plan_for(df.schema)
        self._last_plan = nodes
        self._seg_stats = {}
        self._last_fallbacks = []
        self._pipe_stats = None
        pplan = self._pipe_plan_for(nodes)
        if pplan is not None:
            from ..parallel.pipeplan import StageWedged

            try:
                return self._transform_pipelined(df, nodes, pplan)
            except StageWedged as e:
                # a stage's sub-mesh died mid-stream: quarantine it,
                # re-plan at depth N-1 on the survivors, and re-run the
                # in-flight DataFrame — bitwise-identical either way, so
                # no request is dropped (depth strictly decreases, so the
                # recursion is bounded by the original depth)
                self._pipe_replan_after_wedge(pplan, e.stage)
                return self.transform(df, fused=True)
        for node in nodes:
            if isinstance(node, Segment):
                stats = IngestStats()
                self._seg_stats[node.label] = stats
                ex = self._make_executor(node)
                df = ex.run(df, stats)
                self._last_fallbacks.extend(ex.fallbacks)
            else:
                df = self._host_node(node, df)
        return df

    def _pipe_plan_for(self, nodes: List[Any]):
        """Resolve the pipe_depth knob into a PipePlan (None = serial:
        knob off/<= 1, no mesh, no chainable run, or any resolution
        failure — wrong pipelining must never fail a transform). An
        active CSR layout override keeps the plan serial: wire triples
        are staged per-partition on host, which the device-resident
        handoff never materializes (the same explicit exclusion as
        ``_csr_capable``'s sharding gate)."""
        depth = self._pipe_depth
        if not depth or depth <= 1 or self._shard_mesh is None \
                or self._layout_overrides:
            return None
        try:
            from ..parallel.pipeplan import build_pipe_plan

            pplan = build_pipe_plan(nodes, self._shard_mesh, depth,
                                    model=self._cost_model)
        except Exception:  # noqa: BLE001 — degrade to serial
            return None
        if pplan is not None and self._pipe_supervision is not None:
            try:
                self._pipe_supervision.register(pplan)
            except Exception:  # noqa: BLE001 — registration best-effort
                pass
        return pplan

    def _make_pipe_executor(self, node: Segment,
                            sharding) -> SegmentExecutor:
        """Executor for one pipelined segment: the ordinary
        SegmentExecutor with the stage placement as its sharding. Mega-
        dispatch is forced off (the stream IS the dispatch amortization)
        and the CSR layout is excluded by ``_pipe_plan_for``."""
        return SegmentExecutor(
            node, self._cache,
            buckets=self._bucket_overrides.get(node.label),
            cost_model=self._cost_model,
            slot_pool=self._get_slot_pool(),
            mega_k=1,
            sharding=sharding,
            kernel_variants=self._variant_overrides.get(node.label),
            stitch=self._stitch_overrides or None,
            layout=None)

    def _transform_pipelined(self, df: DataFrame, nodes: List[Any],
                             pplan) -> DataFrame:
        """Execute the plan with its chainable run pipelined: nodes
        before and after the run go through the ordinary serial loop;
        the run's segments stream micro-batches across their stage
        sub-meshes (parallel/pipeplan.py PipeRunner). StageWedged
        escapes to transform(), which re-plans and re-runs. The plan
        indices refer to the PIPELINE VIEW (``split_segments`` re-cut the
        fused chain at d2d boundaries), so that view is what runs —
        serial semantics are identical node-for-node."""
        from ..parallel.ingest import IngestStats
        from ..parallel.pipeplan import PipeRunner, stage_sharding_for

        if pplan.nodes is not None:
            nodes = pplan.nodes

        def serial_node(node, frame):
            if isinstance(node, Segment):
                stats = IngestStats()
                self._seg_stats[node.label] = stats
                ex = self._make_executor(node)
                frame = ex.run(frame, stats)
                self._last_fallbacks.extend(ex.fallbacks)
                return frame
            return self._host_node(node, frame)

        for node in nodes[:pplan.first]:
            df = serial_node(node, df)
        execs, stats = [], []
        for offset, node in enumerate(nodes[pplan.first:pplan.last]):
            stage = pplan.stages[pplan.stage_of[pplan.first + offset]]
            sh = stage_sharding_for(
                node, stage, pplan.depth,
                spec_name=self._sharding_overrides.get(node.label))
            if sh.inner is not None:
                self._seg_sharding[node.label] = sh.inner.describe()
            seg_stats = IngestStats()
            self._seg_stats[node.label] = seg_stats
            stats.append(seg_stats)
            execs.append(self._make_pipe_executor(node, sh))
        runner = PipeRunner(pplan, execs, stats,
                            cost_model=self._cost_model)
        df = runner.run(df)
        for ex in execs:
            self._last_fallbacks.extend(ex.fallbacks)
        self._pipe_stats = runner.stats_dict(
            requeues=self._pipe_requeues, replans=self._pipe_replans)
        for node in nodes[pplan.last:]:
            df = serial_node(node, df)
        return df

    def _pipe_replan_after_wedge(self, pplan, stage_index: int) -> None:
        """Quarantine a wedged stage and re-arm at depth N-1: through the
        registered supervision hook (PipeSupervision — supervisor
        quarantine + mesh degrade) when one is attached, else the local
        degrade. N-1 == 1 clears the knob (serial on the survivors)."""
        self._pipe_replans += 1
        self._pipe_requeues[int(stage_index)] = \
            self._pipe_requeues.get(int(stage_index), 0) + 1
        handler = self._pipe_wedge_handler
        if handler is not None:
            try:
                handler(pplan, int(stage_index))
                return
            except Exception:  # noqa: BLE001 — fall back to local replan
                pass
        from ..parallel.pipeplan import degrade_after_wedge

        mesh, depth = degrade_after_wedge(self._shard_mesh, pplan,
                                          stage_index)
        self.set_mesh(mesh)
        self.set_tuning(pipe_depth=depth if depth > 1 else 1)

    def transform_submit(self, df: DataFrame):
        """Non-blocking transform: run host stages and all but a TRAILING
        fused segment now; the trailing segment's batches are H2D-staged and
        dispatched (device-resident, jax async dispatch) and the returned
        zero-arg ``resolve()`` performs readback + finalize.
        ``transform_submit(df)()`` is bitwise-identical to ``transform(df)``
        — the serving executor uses this split to fulfill replies from its
        dedicated readback thread while the next batch dispatches."""
        from ..parallel.ingest import IngestStats

        nodes = self._plan_for(df.schema)
        self._last_plan = nodes
        self._seg_stats = {}
        self._last_fallbacks = []
        # the submit split stays serial: its contract is a single trailing
        # dispatched segment, not a stream (pipeline stats never linger)
        self._pipe_stats = None
        tail = nodes[-1] if nodes and isinstance(nodes[-1], Segment) else None
        body = nodes[:-1] if tail is not None else nodes
        for node in body:
            if isinstance(node, Segment):
                stats = IngestStats()
                self._seg_stats[node.label] = stats
                ex = self._make_executor(node)
                df = ex.run(df, stats)
                self._last_fallbacks.extend(ex.fallbacks)
            else:
                df = self._host_node(node, df)
        if tail is None:
            out = df
            return lambda: out
        stats = IngestStats()
        self._seg_stats[tail.label] = stats
        ex = self._make_executor(tail)
        resolve = ex.submit_run(df, stats)

        def done() -> DataFrame:
            out = resolve()
            self._last_fallbacks.extend(ex.fallbacks)
            return out

        return done

    # -- stats surface (bench + serving /_mmlspark/stats) -----------------
    @property
    def last_ingest_stats(self):
        """Aggregated ingest decomposition across fused segments of the most
        recent transform (None before the first / when nothing fused)."""
        from ..parallel.ingest import IngestStats

        if not self._seg_stats:
            return None
        agg = IngestStats()
        for s in self._seg_stats.values():
            agg.merge(s)
        return agg

    def fusion_stats(self) -> Dict[str, Any]:
        """Segment layout + per-segment ingest + compile-cache counters +
        XLA cost records and the roofline attribution built from them
        (obs/perf.py): measured-vs-bound per segment with a dominant
        bottleneck label. Cost/roofline sections are empty (never failing)
        when the backend reports no cost analysis."""
        nodes = self._last_plan or []
        per_segment = {label: s.summary()
                       for label, s in self._seg_stats.items()}
        costs = self._cache.costs()
        try:
            from ..obs.perf import attribute_segments

            roofline = attribute_segments(
                per_segment, costs,
                sharding=self._seg_sharding or None,
                cost_model=self._cost_model,
                layout=self._layout_overrides or None)
        except Exception:  # noqa: BLE001 — attribution must not break stats
            roofline = {}
        out = {
            "segments": [n.describe() for n in nodes],
            "n_fused_segments": sum(isinstance(n, Segment) for n in nodes),
            "per_segment": per_segment,
            "fallbacks": list(self._last_fallbacks),
            "compile_cache": self._cache.stats(),
            "segment_costs": costs,
            "roofline": roofline,
        }
        if (self._bucket_overrides or self._fuse_overrides
                or self._mega_k_overrides or self._sharding_overrides
                or self._variant_overrides or self._stitch_overrides
                or self._layout_overrides):
            out["tuning"] = {
                "buckets": {k: list(v)
                            for k, v in self._bucket_overrides.items()},
                "fuse": dict(self._fuse_overrides),
                "mega_k": dict(self._mega_k_overrides),
                "sharding": dict(self._sharding_overrides)}
            # new knobs appear only when set: stats payload parity with
            # plans tuned before the compiler-search knobs existed
            if self._variant_overrides:
                out["tuning"]["kernel_variants"] = {
                    label: {str(b): v for b, v in kv.items()}
                    for label, kv in self._variant_overrides.items()}
            if self._stitch_overrides:
                out["tuning"]["stitch"] = dict(self._stitch_overrides)
            if self._layout_overrides:
                out["tuning"]["layout"] = dict(self._layout_overrides)
        stitched: Dict[str, List[str]] = {}
        for n in nodes:
            if not isinstance(n, Segment):
                continue
            names = list(n.stitched)
            names += [type(s).__name__
                      for s, d in zip(n.stages, n.dfns)
                      if d.device_finalize is not None
                      and d.finalize_stitched is not None
                      and self._stitch_overrides.get(type(s).__name__)]
            if names:
                stitched[n.label] = list(dict.fromkeys(names))
        if stitched:  # key absent when nothing stitched: payload parity
            out["stitched"] = stitched
        if self._seg_sharding:
            from ..parallel.shardplan import mesh_topology

            out["sharding"] = {
                "mesh": mesh_topology(self._shard_mesh),
                "segments": {k: dict(v)
                             for k, v in self._seg_sharding.items()}}
        if self._slot_pool is not None:
            out["slot_pool"] = self._slot_pool.stats()
        if self._pipe_stats:  # key absent when no pipe plan ran: parity
            out["pipeline"] = dict(self._pipe_stats)
        return out

    @property
    def last_fusion_stats(self) -> Dict[str, Any]:
        return self.fusion_stats()

    def save(self, path: str, overwrite: bool = True) -> None:
        PipelineModel(self._stages).save(path, overwrite=overwrite)
