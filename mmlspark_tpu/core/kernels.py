"""Kernel-variant registry for the whole-pipeline compiler search.

The Pallas kernels (GBDT histogram / row-select) and the forest-traversal
kernel each expose a small variant space (tile sizes, grid shapes, loop
order).  Variants are declared here as :class:`KernelVariant` records and
picked per-(segment, bucket) by the Tuner's measure→refit→apply loop; the
fused executor activates the chosen variant around trace time so the kernel
call sites resolve it without threading parameters through every layer.

Two invariants matter:

* **Tolerance declaration.**  ``tolerance is None`` means the variant is
  exact-compute: it must produce bitwise-identical results to the default
  and the Tuner enforces ``array_equal`` during the trial step.  A float
  tolerance marks a reduction-order-sensitive variant (e.g. the histogram
  chunk size changes f32/bf16 accumulation splits) and the trial gates on
  ``allclose(rtol=tol, atol=tol)`` instead.
* **Cold-start identity.**  With no variant active every kernel resolves
  its built-in default; ``active()`` returns ``None`` and no behaviour
  changes.  Variant ids never contain ``:`` or ``;`` so the
  ``variant=<id>;`` CompileCache shape prefix stays unparseable by
  ``bucket_of_shape`` (see core/costmodel.py).
"""

from __future__ import annotations

import contextlib
import re
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, Mapping, Optional, Tuple

__all__ = [
    "KernelVariant",
    "register",
    "get",
    "variants_for",
    "all_variant_ids",
    "activate",
    "active",
    "active_param",
    "DEFAULT_VARIANT",
]

#: Sentinel id for "use the kernel's built-in default" (never registered).
DEFAULT_VARIANT = "default"

_ID_RE = re.compile(r"^[a-z0-9][a-z0-9._-]*$")


@dataclass(frozen=True)
class KernelVariant:
    """One point in a kernel's variant space.

    ``kernel`` names the call-site family ("hist", "select", "forest");
    ``params`` are the knob values the call site consumes at trace time;
    ``tolerance`` is the declared numeric tolerance versus the default
    variant (``None`` = exact-compute, enforced bitwise).
    """

    id: str
    kernel: str
    params: Mapping[str, object] = field(default_factory=dict)
    tolerance: Optional[float] = None

    def __post_init__(self) -> None:
        if not _ID_RE.match(self.id) or ":" in self.id or ";" in self.id:
            raise ValueError(f"invalid kernel variant id: {self.id!r}")


_REGISTRY: Dict[str, KernelVariant] = {}
_LOCK = threading.Lock()


def register(variant: KernelVariant) -> KernelVariant:
    """Register (or idempotently re-register) a variant."""
    with _LOCK:
        prev = _REGISTRY.get(variant.id)
        if prev is not None and prev != variant:
            raise ValueError(f"conflicting redefinition of variant {variant.id!r}")
        _REGISTRY[variant.id] = variant
    return variant


def get(variant_id: str) -> Optional[KernelVariant]:
    """Look up a variant by id; ``None`` for unknown ids / the default."""
    if not variant_id or variant_id == DEFAULT_VARIANT:
        return None
    return _REGISTRY.get(variant_id)


def variants_for(kernel: str) -> Tuple[KernelVariant, ...]:
    with _LOCK:
        return tuple(v for v in _REGISTRY.values() if v.kernel == kernel)


def all_variant_ids() -> Tuple[str, ...]:
    with _LOCK:
        return tuple(sorted(_REGISTRY))


# ---------------------------------------------------------------------------
# Trace-time activation.  The executor enters ``activate(vid)`` around the
# jit trace of a segment build; kernel call sites consult ``active()`` /
# ``active_param()`` *outside* their jit boundary (same pattern as the hist
# kernel's hilo resolution) so the choice becomes a static argument.
# ---------------------------------------------------------------------------

_tls = threading.local()


def _stack(create: bool = False):
    stack = getattr(_tls, "stack", None)
    if stack is None and create:
        stack = _tls.stack = []
    return stack


@contextlib.contextmanager
def activate(variant_id: Optional[str]) -> Iterator[Optional[KernelVariant]]:
    """Make ``variant_id`` the active variant for its kernel family within
    the ``with`` body (thread-local; nestable, innermost wins per family)."""
    var = get(variant_id) if variant_id else None
    if var is None:
        yield None
        return
    stack = _stack(create=True)
    stack.append(var)
    try:
        yield var
    finally:
        stack.pop()


def active(kernel: str) -> Optional[KernelVariant]:
    """The innermost active variant for ``kernel``, or ``None``."""
    stack = _stack()
    if not stack:
        return None
    for var in reversed(stack):
        if var.kernel == kernel:
            return var
    return None


def active_param(kernel: str, name: str, default):
    """Convenience: the active variant's ``params[name]``, else ``default``."""
    var = active(kernel)
    if var is None:
        return default
    return var.params.get(name, default)


# ---------------------------------------------------------------------------
# Built-in variant space.  Kept deliberately small: the Tuner measures each
# candidate, so the space must be affordable to sweep per (segment, bucket).
# ---------------------------------------------------------------------------

# Histogram chunk size changes how rows are split across grid cells and how
# the bf16 hi/lo (or 3-pass f32) partial sums accumulate -> reduction-order
# sensitive, gated behind an allclose tolerance.
_HIST_TOL = 2e-3
for _c in (256, 1024):
    register(KernelVariant(id=f"hist.c{_c}", kernel="hist",
                           params={"chunk": _c}, tolerance=_HIST_TOL))

# Row-select writes each surviving row exactly once via pass-through one-hot
# products; chunking only re-tiles the scan, so variants are exact-compute.
for _c in (512, 2048):
    register(KernelVariant(id=f"select.c{_c}", kernel="select",
                           params={"chunk": _c}, tolerance=None))

# Forest traversal: the path-matrix GEMM and the fori_loop gather traversal
# land on the same leaf values (one-hot reach x leaf value, zeros added
# exactly), so switching loop order is exact-compute.
register(KernelVariant(id="forest.gather", kernel="forest",
                       params={"impl": "gather"}, tolerance=None))
register(KernelVariant(id="forest.gemm", kernel="forest",
                       params={"impl": "gemm"}, tolerance=None))

# Sparse/CSR kernels (gbdt/pallas_sparse.py, docs/sparse.md):
#   hist.csr — the sparse engine's flat-ragged-bin histogram as a one-hot
#   MXU contraction over nnz chunks; chunk order changes the f32 summation
#   order versus the prefix-sum path, so it shares the histogram tolerance.
#   forest.csr — forest traversal over the CSR-gathered used-feature
#   matrix, with the gather itself on the MXU; every output cell of the
#   gather receives at most one nonzero, so the variant is exact-compute.
register(KernelVariant(id="hist.csr", kernel="hist",
                       params={"layout": "csr"}, tolerance=_HIST_TOL))
register(KernelVariant(id="forest.csr", kernel="forest",
                       params={"impl": "gather", "csr_gather": "pallas"},
                       tolerance=None))
