"""Per-segment cost model: analytical roofline first, measured refinement on top.

BENCH_mfu_roofline.json bounds the image chain at ~16,000 images/s while
BENCH_image_e2e.json measures ~65 end-to-end — and every knob governing that
gap (shape buckets, fuse-vs-demote, coalesce window, inflight/replica
sizing) is a hand-tuned constant. PR 7 built the measurement substrate
(per-(segment, shape-bucket) XLA cost harvest in the CompileCache +
IngestStats queue/h2d/compute/readback decomposition); this module is the
model those measurements train, in the shape of "A Learned Performance
Model for TPUs" (arXiv:2008.01040): start from an ANALYTICAL prediction
(roofline over harvested flops/bytes and ``device_peaks()``, plus
compile-time amortization for buckets that would need a fresh executable),
then REFINE online from what the rings actually measured (per-stage EWMAs
keyed by ``(segment, bucket)``).

The public surface the Tuner (core/tune.py) consumes:

  - ``observe_batch(segment, timing)`` / ``observe_stats(segment, stats)``
    fold measured ``BatchTiming`` rows in (bucket = the padded batch size).
  - ``ingest_costs(cache.costs())`` folds the CompileCache's harvested
    flops / bytes_accessed / compile_s records.
  - ``observe_host(stage, seconds, rows)`` learns the HOST path's per-row
    cost per stage class — the other side of the fuse-vs-demote comparison.
  - ``predict_ms(segment, shape=None, batch=None)`` -> predicted wall ms
    for one batch, or None when the model knows nothing; ``predict()``
    returns the full record (per-stage parts, source, confidence).
  - ``confidence(segment)`` in [0, 1]: 0 = nothing known, low = analytical
    only, -> 1 as measured batches accumulate. ``calibrated(segment)`` is
    the gate every knob decision sits behind: an UNCALIBRATED model must
    change nothing (cold-start behavior stays bitwise-identical).
  - ``choose_buckets(segment, max_bucket)`` -> a bucket set minimizing
    predicted pad-waste + compile amortization over the OBSERVED batch-size
    histogram (None until calibrated — callers keep the power-of-two
    default, ``parallel/batching.py next_bucket``).
  - ``fuse_decision(segment_label)`` -> True/False when both the device
    prediction and the summed host-stage measurements are trustworthy,
    None otherwise (the planner then falls back to the light-segment
    heuristic, core/fusion.py plan()).
  - ``observe_variant(segment, bucket, variant, seconds)`` folds measured
    kernel-variant trials; ``choose_variant(segment, bucket)`` returns the
    per-(segment, bucket) winner (None keeps the built-in default);
    ``stitch_decision(upstream, downstream)`` prices a cross-segment
    stitch against the measured readback + H2D round-trip it removes —
    both gated on calibration so cold start stays bitwise-identical.
  - ``observe_collective(op, nbytes, seconds)`` folds measured
    all-reduce / all-gather probe times (parallel/shardplan.py
    ``measure_collectives``); ``collective_ms(op, nbytes)`` is the fitted
    α·bytes + latency term, and ``choose_sharding(segment, batch,
    candidates)`` prices each candidate partitioning as the per-shard
    batch prediction plus its collective term — returning the winning
    spec name, or None (= stay unsharded, the bitwise default) until BOTH
    the segment and the collectives are calibrated.
  - ``predict_pipelined_ms(stage_labels, batch)`` prices a pipeline as its
    slowest stage paid ``M + S - 1`` ticks (the GPipe fill/drain bubble)
    plus the fitted ``pipe_handoff`` transfer term, and
    ``choose_pipe_depth(chain_labels, batch, max_depth)`` picks the depth
    whose best contiguous stage grouping undercuts the serial wall — or
    None (= stay serial, the bitwise default), gated on calibration
    exactly like ``choose_sharding``.

Everything is host-side Python (no jax import), thread-safe under one lock,
and serializable (``to_dict``/``from_dict``) so a tuned model survives a
server restart or ships to a replica.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["SegmentCostModel", "bucket_of_shape"]

#: measured-stage keys folded per (segment, bucket); queue_s is tracked but
#: excluded from the predicted batch wall (it is producer wait the ring
#: overlaps, not work the batch itself costs)
_STAGES = ("queue_s", "h2d_s", "dispatch_s", "compute_s", "readback_s")
_WALL_STAGES = ("h2d_s", "dispatch_s", "compute_s", "readback_s")


def bucket_of_shape(shape_key: str) -> Optional[int]:
    """Leading (batch) dim of a CompileCache shape key
    (``"col=64x32x32x3:uint8;..."`` -> 64); None when unparseable.

    The first token must be a structurally valid SHAPE entry —
    ``<col>=<d1>x...x<dn>:<dtype>`` with every dim an integer — so ANY
    decorated prefix (``mega{k};``, ``spec=...;``, ``variant=<id>;``,
    ``stitch=...;`` or future ones) is rejected generically rather than by
    per-prefix special cases. Decorated keys carry executor state, not a
    batch shape; parsing one here would leak a bogus bucket into the
    analytic cost tables."""
    try:
        first = shape_key.split(";", 1)[0]
        name, eq, value = first.partition("=")
        if not eq or not name or "{" in name or "}" in name:
            return None
        dims, colon, dtype = value.rpartition(":")
        if not colon or not dtype or not dims:
            return None
        parts = dims.split("x")
        if not all(p.isdigit() for p in parts):
            return None
        return int(parts[0])
    except (IndexError, ValueError):
        return None


def _min_max_contiguous(costs: Sequence[float], k: int) -> float:
    """Minimum achievable max-stage-sum over contiguous splits of ``costs``
    into ``k`` groups — the pipeline clock of the best-balanced contiguous
    stage assignment (chains are short, so enumerate cut placements)."""
    vals = [float(c) for c in costs]
    n = len(vals)
    k = max(1, min(int(k), n))
    if k == 1:
        return sum(vals)
    import itertools
    best = float("inf")
    for cuts in itertools.combinations(range(1, n), k - 1):
        bounds = (0,) + cuts + (n,)
        clock = max(sum(vals[a:b]) for a, b in zip(bounds, bounds[1:]))
        best = min(best, clock)
    return best


class _BucketRecord:
    """Measured EWMAs + counters for one (segment, bucket).

    ``dispatch_call_s`` tracks the DE-AMORTIZED per-Python-call dispatch
    cost: when a timing rode a K-step mega dispatch (``timing.mega_k`` >
    1), its ``dispatch_s`` is the per-batch share (mega time / K), so the
    call cost is ``dispatch_s * mega_k``. ``choose_mega_k`` reads this —
    reading the amortized EWMA would make an active K>1 look like cheap
    dispatch, propose K=1, and oscillate every tuning cycle. The amortized
    ``dispatch_s`` EWMA stays as-is: it IS the per-batch wall
    contribution the roofline/prediction side wants."""

    __slots__ = ("n", "rows", "ewma", "dispatch_call_s") + _STAGES

    def __init__(self):
        self.n = 0
        self.rows = 0
        self.dispatch_call_s = None
        for k in _STAGES:
            setattr(self, k, None)

    def fold(self, timing, alpha: float) -> None:
        self.n += 1
        self.rows += int(getattr(timing, "rows", 0) or 0)
        for k in _STAGES:
            v = float(getattr(timing, k, 0.0) or 0.0) * 1e3  # -> ms
            prev = getattr(self, k)
            setattr(self, k, v if prev is None
                    else (1 - alpha) * prev + alpha * v)
        k_amort = max(1, int(getattr(timing, "mega_k", 1) or 1))
        call = float(getattr(timing, "dispatch_s", 0.0) or 0.0) * \
            k_amort * 1e3
        self.dispatch_call_s = call if self.dispatch_call_s is None \
            else (1 - alpha) * self.dispatch_call_s + alpha * call

    def wall_ms(self) -> Optional[float]:
        vals = [getattr(self, k) for k in _WALL_STAGES]
        if all(v is None for v in vals):
            return None
        return sum(v for v in vals if v is not None)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"n": self.n, "rows": self.rows}
        for k in _STAGES:
            v = getattr(self, k)
            if v is not None:
                out[k[:-2] + "_ms"] = round(v, 6)
        if self.dispatch_call_s is not None:
            out["dispatch_call_ms"] = round(self.dispatch_call_s, 6)
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "_BucketRecord":
        rec = cls()
        rec.n = int(d.get("n", 0))
        rec.rows = int(d.get("rows", 0))
        for k in _STAGES:
            v = d.get(k[:-2] + "_ms")
            if v is not None:
                setattr(rec, k, float(v))
        v = d.get("dispatch_call_ms")
        if v is not None:
            rec.dispatch_call_s = float(v)
        return rec


class SegmentCostModel:
    """Analytical-then-learned per-(segment, bucket) batch cost model."""

    def __init__(self, peaks: Optional[Dict[str, Any]] = None,
                 ewma: float = 0.3, min_obs: int = 4,
                 compile_horizon: int = 200):
        # peaks resolve lazily (device_peaks() may init a jax backend the
        # caller hasn't touched yet); pass explicitly to pin them
        self._peaks = peaks
        self.ewma = float(ewma)
        #: batches measured at a bucket before its EWMA is trusted
        self.min_obs = int(min_obs)
        #: batches a fresh compile is amortized over in bucket-set scoring
        self.compile_horizon = int(compile_horizon)
        self._lock = threading.Lock()
        # (segment, bucket) -> measured record
        self._measured: Dict[Tuple[str, int], _BucketRecord] = {}
        # (segment, bucket) -> {flops, bytes_accessed, compile_s} (harvest)
        self._analytic: Dict[Tuple[str, int], Dict[str, float]] = {}
        # segment -> {real batch rows -> batches observed} (pad-waste term)
        self._size_hist: Dict[str, Dict[int, int]] = {}
        # host stage class -> (ewma ms-per-row, n) — the demote side
        self._host: Dict[str, List[float]] = {}
        # collective op ("all_reduce"/"all_gather") -> [(bytes, ms), ...]
        # measured probe points (bounded), the α·bytes sharding term
        self._collective: Dict[str, List[Tuple[float, float]]] = {}
        # (segment, bucket, variant id) -> [ewma wall ms, n] — measured
        # kernel-variant trials ("default" tracks the incumbent baseline)
        self._variant: Dict[Tuple[str, int, str], List[float]] = {}
        # segment -> [ewma nnz-per-row, ewma width, n] — sparse density
        # observations (docs/sparse.md): staging bytes scale with nnz, not
        # rows x width, so the layout decision needs its own term
        self._nnz: Dict[str, List[float]] = {}

    # -- feeding ---------------------------------------------------------
    def peaks(self) -> Dict[str, Any]:
        if self._peaks is None:
            from ..obs.perf import device_peaks

            self._peaks = device_peaks()
        return self._peaks

    def observe_batch(self, segment: str, timing) -> None:
        """Fold one measured ``BatchTiming`` (parallel/ingest.py). Bucket =
        the padded batch size when recorded, else the valid row count."""
        bucket = int(getattr(timing, "padded_rows", 0) or 0) or \
            int(getattr(timing, "rows", 0) or 0)
        if bucket <= 0:
            return
        rows = int(getattr(timing, "rows", 0) or 0)
        with self._lock:
            key = (str(segment), bucket)
            rec = self._measured.get(key)
            if rec is None:
                rec = self._measured[key] = _BucketRecord()
            rec.fold(timing, self.ewma)
            if rows > 0:
                hist = self._size_hist.setdefault(str(segment), {})
                hist[rows] = hist.get(rows, 0) + 1

    def observe_stats(self, segment: str, stats, start: int = 0) -> int:
        """Fold ``stats.records[start:]`` of an IngestStats; returns the new
        high-water index (incremental folding without double counting)."""
        records = list(getattr(stats, "records", ()))[start:]
        for t in records:
            self.observe_batch(segment, t)
        return start + len(records)

    def ingest_costs(self, costs: Dict[str, Dict[str, Dict[str, Any]]]
                     ) -> None:
        """Fold a ``CompileCache.costs()`` payload: {segment: {shape key:
        {flops, bytes_accessed, compile_s, ...}}} keyed down to buckets."""
        with self._lock:
            for label, shapes in (costs or {}).items():
                for shape, rec in shapes.items():
                    bucket = bucket_of_shape(shape)
                    if bucket is None or bucket <= 0:
                        continue
                    dst = self._analytic.setdefault(
                        (str(label), bucket), {})
                    for k in ("flops", "bytes_accessed", "compile_s",
                              "output_bytes", "argument_bytes"):
                        v = rec.get(k)
                        if isinstance(v, (int, float)):
                            dst[k] = float(v)

    def observe_host(self, stage: str, seconds: float, rows: int) -> None:
        """Fold one host-path stage execution (ms per row EWMA)."""
        if rows <= 0 or seconds < 0:
            return
        per_row = seconds * 1e3 / rows
        with self._lock:
            cur = self._host.get(str(stage))
            if cur is None:
                self._host[str(stage)] = [per_row, 1]
            else:
                cur[0] = (1 - self.ewma) * cur[0] + self.ewma * per_row
                cur[1] += 1

    def observe_collective(self, op: str, nbytes: float, seconds: float
                           ) -> None:
        """Fold one measured collective probe (parallel/shardplan.py
        ``measure_collectives``): op is ``"all_reduce"``/``"all_gather"``,
        ``nbytes`` the payload size, ``seconds`` the measured wall time."""
        if nbytes <= 0 or seconds < 0:
            return
        with self._lock:
            pts = self._collective.setdefault(str(op), [])
            pts.append((float(nbytes), float(seconds) * 1e3))
            if len(pts) > 64:  # bound: keep the freshest calibration
                del pts[:-64]

    def _collective_fit(self, op: str) -> Optional[Tuple[float, float]]:
        """(latency_ms, ms_per_byte) least-squares fit over the probe
        points for one op; None when no points exist."""
        pts = self._collective.get(str(op))
        if not pts:
            return None
        if len(pts) == 1 or len({b for b, _ in pts}) == 1:
            b0, ms0 = pts[-1]
            return 0.0, ms0 / b0  # proportional through the origin
        n = float(len(pts))
        sx = sum(b for b, _ in pts)
        sy = sum(m for _, m in pts)
        sxx = sum(b * b for b, _ in pts)
        sxy = sum(b * m for b, m in pts)
        denom = n * sxx - sx * sx
        slope = (n * sxy - sx * sy) / denom
        alpha = (sy - slope * sx) / n
        return max(0.0, alpha), max(0.0, slope)

    def collective_ms(self, op: str, nbytes: float) -> Optional[float]:
        """Predicted wall ms of one ``op`` collective moving ``nbytes``
        (fitted latency + α·bytes); None until a probe has been folded."""
        with self._lock:
            fit = self._collective_fit(op)
        if fit is None or nbytes < 0:
            return None
        alpha, per_byte = fit
        return alpha + per_byte * float(nbytes)

    def collective_calibrated(self, op: Optional[str] = None) -> bool:
        """True once measured probes back the op's collective term (any op
        when None) — the second gate in front of ``choose_sharding``."""
        with self._lock:
            ops = [str(op)] if op else list(self._collective)
            return any(len(self._collective.get(o) or ()) >= 2
                       for o in ops)

    def segment_bytes(self, segment: str, key: str = "output_bytes"
                      ) -> Optional[float]:
        """Mean harvested byte count over the segment's analytic records
        (``output_bytes``/``argument_bytes``/``bytes_accessed``) — the
        collective payload estimate ``choose_sharding`` candidates carry."""
        with self._lock:
            vals = [rec[key] for (s, _), rec in self._analytic.items()
                    if s == str(segment) and isinstance(
                        rec.get(key), (int, float))]
        return sum(vals) / len(vals) if vals else None

    # -- prediction ------------------------------------------------------
    def _analytic_ms(self, key: Tuple[str, int]) -> Optional[float]:
        rec = self._analytic.get(key)
        if not rec:
            return None
        peaks = self.peaks()
        t_f = rec.get("flops", 0.0) / float(peaks["flops"])
        t_b = rec.get("bytes_accessed", 0.0) / float(peaks["bytes_per_s"])
        bound = max(t_f, t_b)
        return bound * 1e3 if bound > 0 else None

    def _buckets_of(self, segment: str) -> List[int]:
        return sorted({b for (s, b) in self._measured if s == segment} |
                      {b for (s, b) in self._analytic if s == segment})

    def _ms_at_bucket(self, segment: str, bucket: int
                      ) -> Tuple[Optional[float], str, float]:
        """(predicted ms, source, confidence) at one exact bucket.

        Measured EWMA when trusted; else analytical roofline, scaled by the
        segment's measured/bound ratio when any bucket of the segment has
        both (the "learned correction" on top of the analytical form)."""
        key = (segment, bucket)
        rec = self._measured.get(key)
        if rec is not None and rec.n >= self.min_obs:
            wall = rec.wall_ms()
            if wall is not None:
                return wall, "measured", rec.n / (rec.n + float(self.min_obs))
        bound = self._analytic_ms(key)
        if bound is None:
            return None, "none", 0.0
        # correction factor: mean measured/bound over calibrated buckets
        ratios = []
        for (s, b), m in self._measured.items():
            if s != segment or m.n < self.min_obs:
                continue
            other = self._analytic_ms((segment, b))
            wall = m.wall_ms()
            if other and wall and other > 0:
                ratios.append(wall / other)
        if ratios:
            return (bound * sum(ratios) / len(ratios), "analytic+corrected",
                    0.3)
        return bound, "analytic", 0.1

    def _interp_ms(self, segment: str, bucket: int
                   ) -> Tuple[Optional[float], str, float]:
        """Prediction at an ARBITRARY bucket: exact record when present,
        else linear interpolation/extrapolation over the known buckets
        (batch cost is affine in rows to first order: fixed dispatch +
        per-row compute)."""
        exact = self._ms_at_bucket(segment, bucket)
        if exact[0] is not None:
            return exact
        pts = []
        for b in self._buckets_of(segment):
            ms, _, conf = self._ms_at_bucket(segment, b)
            if ms is not None:
                pts.append((b, ms, conf))
        if not pts:
            return None, "none", 0.0
        if len(pts) == 1:
            b0, ms0, conf = pts[0]
            # proportional with a fixed-cost floor: half the known point
            return ms0 * max(0.5, bucket / b0), "scaled", conf * 0.5
        pts.sort()
        lo = max((p for p in pts if p[0] <= bucket), default=pts[0])
        hi = min((p for p in pts if p[0] >= bucket), default=pts[-1])
        if lo[0] == hi[0]:
            lo, hi = pts[0], pts[-1]
        slope = (hi[1] - lo[1]) / float(hi[0] - lo[0])
        ms = lo[1] + slope * (bucket - lo[0])
        conf = min(lo[2], hi[2]) * 0.8
        return max(ms, 1e-6), "interpolated", conf

    def predict(self, segment: str, batch: Optional[int] = None,
                shape: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Full prediction record for one batch of ``batch`` rows (or the
        bucket parsed from a CompileCache ``shape`` key): ``{ms, bucket,
        source, confidence, parts}`` or None when the model knows nothing
        about the segment."""
        if batch is None and shape is not None:
            batch = bucket_of_shape(shape)
        if batch is None or batch <= 0:
            return None
        with self._lock:
            ms, source, conf = self._interp_ms(str(segment), int(batch))
            if ms is None:
                return None
            out: Dict[str, Any] = {"ms": round(ms, 6), "bucket": int(batch),
                                   "source": source,
                                   "confidence": round(conf, 4)}
            rec = self._measured.get((str(segment), int(batch)))
            if rec is not None and rec.n > 0:
                out["parts"] = {k[:-2] + "_ms": round(getattr(rec, k), 6)
                                for k in _STAGES
                                if getattr(rec, k) is not None}
                out["observed_batches"] = rec.n
            return out

    def predict_ms(self, segment: str, shape: Optional[str] = None,
                   batch: Optional[int] = None) -> Optional[float]:
        rec = self.predict(segment, batch=batch, shape=shape)
        return None if rec is None else rec["ms"]

    def per_row_ms(self, segment: str, batch: int = 32) -> Optional[float]:
        """Predicted per-ROW service at bucket ``batch`` — the packing key
        of the multimodel planner (``predict_ms x forecast_rps``,
        serving/fleet/planner.py pack_models). None while uncalibrated:
        the planner gives the model a measured-probe slot instead."""
        if batch <= 0:
            return None
        ms = self.predict_ms(segment, batch=int(batch))
        return None if ms is None else ms / int(batch)

    def confidence(self, segment: str) -> float:
        """Calibration confidence for a segment: the best single-bucket
        confidence (0.0 = unknown, >= 0.5 once min_obs batches measured)."""
        with self._lock:
            best = 0.0
            for b in self._buckets_of(str(segment)):
                _, _, conf = self._ms_at_bucket(str(segment), b)
                best = max(best, conf)
            return round(best, 4)

    def calibrated(self, segment: Optional[str] = None) -> bool:
        """True once MEASURED data (not just analytical bounds) backs the
        segment — the gate in front of every knob change."""
        with self._lock:
            keys = [k for k in self._measured
                    if segment is None or k[0] == str(segment)]
            return any(self._measured[k].n >= self.min_obs for k in keys)

    # -- knob decisions --------------------------------------------------
    def choose_buckets(self, segment: str, max_bucket: int,
                       max_buckets: int = 6,
                       candidates: Optional[Sequence[int]] = None
                       ) -> Optional[Tuple[int, ...]]:
        """Bucket set minimizing predicted batch cost + compile
        amortization over the segment's OBSERVED batch-size histogram.

        Candidates default to the observed real sizes, their next multiples
        of 8, and the power-of-two defaults (all capped at ``max_bucket``).
        Every observed size must map to the smallest chosen bucket >= it;
        each chosen bucket that has never compiled charges its predicted
        compile time amortized over ``compile_horizon`` batches. Returns
        None until the segment is calibrated — the caller then keeps the
        power-of-two default, so an uncalibrated model changes nothing."""
        seg = str(segment)
        if not self.calibrated(seg):
            return None
        with self._lock:
            hist = dict(self._size_hist.get(seg) or {})
        hist = {n: c for n, c in hist.items() if 0 < n <= max_bucket}
        if not hist:
            return None
        if candidates is None:
            cand = set()
            for n in hist:
                cand.add(n)
                cand.add(min(max_bucket, (n + 7) // 8 * 8))
            b = 8
            while b < max_bucket:
                cand.add(b)
                b <<= 1
            cand.add(max_bucket)
            candidates = sorted(c for c in cand if c >= 1)
        else:
            candidates = sorted({int(c) for c in candidates
                                 if 0 < int(c) <= max_bucket})
        if not candidates or candidates[-1] < max(hist):
            return None
        with self._lock:
            compiled = {b for (s, b) in self._analytic if s == seg} | \
                {b for (s, b) in self._measured if s == seg}
            ms_at = {}
            for c in candidates:
                ms, _, _ = self._interp_ms(seg, c)
                if ms is None:
                    return None
                ms_at[c] = ms
            compile_ms = [rec.get("compile_s", 0.0) * 1e3
                          for (s, _), rec in self._analytic.items()
                          if s == seg and rec.get("compile_s")]
        amort = (sum(compile_ms) / len(compile_ms) / self.compile_horizon
                 if compile_ms else 0.0)

        def score(chosen: Tuple[int, ...]) -> float:
            total = 0.0
            for n, count in hist.items():
                b = next((c for c in chosen if c >= n), chosen[-1])
                total += count * ms_at[b]
            total += sum(amort for b in chosen if b not in compiled)
            return total

        # exact search over small candidate sets, greedy refinement above
        best: Optional[Tuple[int, ...]] = None
        best_score = float("inf")
        top = candidates[-1]
        rest = candidates[:-1]
        if len(rest) <= 12:
            for mask in range(1 << len(rest)):
                chosen = tuple(c for i, c in enumerate(rest)
                               if mask >> i & 1) + (top,)
                if len(chosen) > max_buckets:
                    continue
                s = score(chosen)
                if s < best_score - 1e-12:
                    best, best_score = chosen, s
        else:
            chosen = (top,)
            best, best_score = chosen, score(chosen)
            improved = True
            while improved and len(best) < max_buckets:
                improved = False
                for c in rest:
                    if c in best:
                        continue
                    trial = tuple(sorted(best + (c,)))
                    s = score(trial)
                    if s < best_score - 1e-12:
                        best, best_score = trial, s
                        improved = True
        return best

    def fuse_decision(self, label: str) -> Optional[bool]:
        """Predicted fuse-vs-host comparison for a segment label
        (``"StageA+StageB"``): True when the predicted DEVICE per-row cost
        undercuts the summed measured HOST per-row cost of its stages,
        False when it doesn't, None when either side lacks trustworthy data
        (the planner keeps the light-segment heuristic)."""
        seg = str(label)
        if not self.calibrated(seg):
            return None
        with self._lock:
            host_total = 0.0
            for stage in seg.split("+"):
                rec = self._host.get(stage)
                if rec is None or rec[1] < self.min_obs:
                    return None
                host_total += rec[0]
            # device ms/row at the modal measured bucket
            best_key, best_n = None, 0
            for (s, b), rec in self._measured.items():
                if s == seg and rec.n > best_n and rec.rows > 0:
                    best_key, best_n = (s, b), rec.n
            if best_key is None or best_n < self.min_obs:
                return None
            rec = self._measured[best_key]
            wall = rec.wall_ms()
            if wall is None:
                return None
            device_per_row = wall * rec.n / rec.rows
        return device_per_row < host_total

    def choose_mega_k(self, segment: str, max_k: int = 8,
                      amortize_to: float = 0.15) -> Optional[int]:
        """Dispatch-amortization factor for a segment: the K micro-batches a
        single Python-level mega-dispatch should cover so the measured fixed
        dispatch cost falls to ``amortize_to`` of the per-batch device work
        (H2D + compute + readback EWMAs at the modal measured bucket).
        Returns None when uncalibrated or the modal bucket lacks a dispatch
        measurement; 1 when dispatch is already cheap enough. Reads the
        DE-AMORTIZED per-call dispatch EWMA (``dispatch_call_s``), so the
        chosen K stays stable while a K>1 mega dispatch is active instead
        of oscillating back to 1 on its own amortized timings."""
        seg = str(segment)
        if not self.calibrated(seg):
            return None
        with self._lock:
            best_rec, best_n = None, 0
            for (s, _b), rec in self._measured.items():
                if s == seg and rec.n > best_n:
                    best_rec, best_n = rec, rec.n
            if best_rec is None or best_n < self.min_obs:
                return None
            disp = best_rec.dispatch_call_s
            if disp is None:
                disp = best_rec.dispatch_s
            if disp is None or disp <= 0.0:
                return None
            work = sum(v for v in (best_rec.h2d_s, best_rec.compute_s,
                                   best_rec.readback_s) if v is not None)
        if work <= 0.0:
            return None
        if disp <= amortize_to * work:
            return 1
        k = int(math.ceil(disp / (amortize_to * work)))
        return max(1, min(int(max_k), k))

    def predict_sharded_ms(self, segment: str, batch: int, shards: int,
                           collective_bytes: float = 0.0,
                           op: str = "all_gather") -> Optional[float]:
        """Predicted wall ms for one ``batch``-row dispatch sharded
        ``shards`` ways: the single-device prediction at the PER-SHARD
        batch (ceil(batch/shards) — compute and memory traffic divide
        across chips) plus the measured collective term for moving
        ``collective_bytes`` through ``op``. None when the segment
        prediction is unknown, or when a nonzero collective payload has no
        calibrated term (an unpriced collective must not look free)."""
        shards = max(1, int(shards))
        per_shard = (int(batch) + shards - 1) // shards
        base = self.predict_ms(segment, batch=per_shard)
        if base is None:
            return None
        coll = 0.0
        if collective_bytes > 0:
            fitted = self.collective_ms(op, collective_bytes)
            if fitted is None:
                return None
            coll = fitted
        return base + coll

    def choose_sharding(self, segment: str, batch: int,
                        candidates: Sequence[Dict[str, Any]],
                        margin: float = 0.95) -> Optional[str]:
        """Pick the candidate partitioning (``{name, shards, op,
        collective_bytes}`` descriptions from ``shardplan.
        tuner_candidates``) whose predicted sharded wall undercuts the
        unsharded prediction by at least ``1 - margin``; None keeps the
        segment unsharded. Gated on BOTH ``calibrated(segment)`` and
        ``collective_calibrated()``: an uncalibrated model must change
        nothing, so cold-start stays bitwise-identical to the single-device
        path."""
        seg = str(segment)
        if not self.calibrated(seg) or not self.collective_calibrated():
            return None
        base = self.predict_ms(seg, batch=int(batch))
        if base is None:
            return None
        best_name: Optional[str] = None
        best_ms = base * float(margin)
        for cand in candidates or ():
            shards = int(cand.get("shards", 1) or 1)
            if shards <= 1:
                continue
            ms = self.predict_sharded_ms(
                seg, int(batch), shards,
                collective_bytes=float(cand.get("collective_bytes", 0.0)
                                       or 0.0),
                op=str(cand.get("op", "all_gather")))
            if ms is not None and ms < best_ms:
                best_ms = ms
                best_name = str(cand.get("name"))
        return best_name

    def predict_pipelined_ms(self, stage_labels: Sequence[str], batch: int,
                             microbatches: int = 8,
                             handoff_bytes: float = 0.0,
                             op: str = "pipe_handoff") -> Optional[float]:
        """Predicted wall ms for streaming ``microbatches`` micro-batches
        of ``batch`` rows through pipeline stages whose segment labels are
        ``stage_labels``: the pipeline clock is its slowest stage, paid
        ``M + S - 1`` ticks (the GPipe fill/drain bubble), plus the fitted
        inter-stage transfer term for the ``M * (S - 1)`` device-to-device
        handoffs. Gated exactly like :meth:`choose_sharding`: None unless
        EVERY stage is calibrated and a nonzero handoff payload has a
        fitted transfer cost — an unpriced pipeline must not look free, so
        cold start stays bitwise-identical to the unpipelined path."""
        labels = [str(s) for s in stage_labels]
        if not labels:
            return None
        per: list = []
        for lab in labels:
            if not self.calibrated(lab):
                return None
            ms = self.predict_ms(lab, batch=int(batch))
            if ms is None:
                return None
            per.append(ms)
        n_stages = len(per)
        hand = 0.0
        if handoff_bytes > 0 and n_stages > 1:
            fitted = self.collective_ms(op, handoff_bytes)
            if fitted is None:
                return None
            hand = fitted
        m = max(1, int(microbatches))
        return (m + n_stages - 1) * max(per) + m * (n_stages - 1) * hand

    def choose_pipe_depth(self, chain_labels: Sequence[str], batch: int,
                          max_depth: int, microbatches: int = 8,
                          handoff_bytes: float = 0.0,
                          op: str = "pipe_handoff",
                          margin: float = 0.95) -> Optional[int]:
        """Pipeline depth for a chainable segment run: the best contiguous
        grouping of ``chain_labels`` into 2..``max_depth`` stages (each
        stage's cost is the sum of its members, the clock their max) whose
        predicted pipelined wall undercuts the serial wall by at least
        ``1 - margin``. None keeps the chain serial. Gated on every label
        being ``calibrated`` and — for a nonzero handoff payload — on a
        fitted ``op`` transfer term, mirroring :meth:`choose_sharding` so
        an uncalibrated model changes nothing."""
        labels = [str(s) for s in chain_labels]
        if len(labels) < 2 or int(max_depth) < 2:
            return None
        per: list = []
        for lab in labels:
            if not self.calibrated(lab):
                return None
            ms = self.predict_ms(lab, batch=int(batch))
            if ms is None:
                return None
            per.append(ms)
        hand = 0.0
        if handoff_bytes > 0:
            if not self.collective_calibrated(op):
                return None
            fitted = self.collective_ms(op, handoff_bytes)
            if fitted is None:
                return None
            hand = fitted
        m = max(1, int(microbatches))
        serial = m * sum(per)
        best_depth: Optional[int] = None
        best_ms = serial * float(margin)
        for depth in range(2, min(int(max_depth), len(per)) + 1):
            clock = _min_max_contiguous(per, depth)
            total = (m + depth - 1) * clock + m * (depth - 1) * hand
            if total < best_ms:
                best_ms = total
                best_depth = depth
        return best_depth

    def _modal_record(self, segment: str) -> Optional[_BucketRecord]:
        """Most-observed measured record of a segment when it clears
        ``min_obs``; caller holds the lock."""
        best, best_n = None, 0
        for (s, _b), rec in self._measured.items():
            if s == segment and rec.n > best_n:
                best, best_n = rec, rec.n
        return best if best is not None and best_n >= self.min_obs else None

    def stitch_decision(self, upstream: str, downstream: str,
                        margin: float = 0.95) -> Optional[bool]:
        """Should the planner stitch ``downstream`` into ``upstream``'s
        segment across a transpiled host shim? True when the measured
        round-trip the merge removes — upstream readback + downstream H2D +
        downstream dispatch EWMAs at the modal buckets — is worth at least
        ``1 - margin`` of the combined measured wall (``predict_ms`` backs
        the walls). None until BOTH sides are calibrated: an uncalibrated
        model must change nothing, so cold-start plans stay
        bitwise-identical."""
        up, down = str(upstream), str(downstream)
        if not self.calibrated(up) or not self.calibrated(down):
            return None
        with self._lock:
            up_rec = self._modal_record(up)
            down_rec = self._modal_record(down)
            if up_rec is None or down_rec is None:
                return None
            saved = sum(v for v in (up_rec.readback_s, down_rec.h2d_s,
                                    down_rec.dispatch_s) if v is not None)
            walls = [r.wall_ms() for r in (up_rec, down_rec)]
        if saved <= 0.0 or any(w is None for w in walls):
            return None
        return saved > (1.0 - float(margin)) * sum(walls)

    def observe_variant(self, segment: str, bucket: int, variant: str,
                        seconds: float) -> None:
        """Fold one measured kernel-variant trial at (segment, bucket);
        variant ``"default"`` tracks the incumbent baseline the candidates
        must beat."""
        if seconds < 0 or bucket <= 0:
            return
        ms = float(seconds) * 1e3
        with self._lock:
            key = (str(segment), int(bucket), str(variant))
            cur = self._variant.get(key)
            if cur is None:
                self._variant[key] = [ms, 1]
            else:
                cur[0] = (1 - self.ewma) * cur[0] + self.ewma * ms
                cur[1] += 1

    def variant_buckets(self, segment: str) -> List[int]:
        """Buckets of a segment that have any kernel-variant trial data."""
        with self._lock:
            return sorted({b for (s, b, _v) in self._variant
                           if s == str(segment)})

    def choose_variant(self, segment: str, bucket: int,
                       margin: float = 0.95) -> Optional[str]:
        """Winning kernel variant at one (segment, bucket): the candidate
        whose trial EWMA undercuts the measured ``"default"`` baseline by
        at least ``1 - margin``, both sides backed by ``min_obs`` trials.
        None keeps the built-in default — so with no trials folded (cold
        start) nothing changes."""
        seg, b = str(segment), int(bucket)
        with self._lock:
            base = self._variant.get((seg, b, "default"))
            if base is None or base[1] < self.min_obs:
                return None
            best_id: Optional[str] = None
            best_ms = base[0] * float(margin)
            for (s, bb, vid), rec in sorted(self._variant.items()):
                if s != seg or bb != b or vid == "default":
                    continue
                if rec[1] >= self.min_obs and rec[0] < best_ms:
                    best_id, best_ms = vid, rec[0]
        return best_id

    def observe_nnz(self, segment: str, rows: int, nnz: int,
                    width: int) -> None:
        """Fold one sparse-column staging observation (rows of the
        partition, total nonzeros, declared feature width) — fed by the
        executor's CSR/densify staging and by bench harnesses. The EWMA
        tracks nnz PER ROW so the prediction scales to any batch size."""
        if rows <= 0 or nnz < 0 or width <= 0:
            return
        per_row = float(nnz) / float(rows)
        with self._lock:
            cur = self._nnz.get(str(segment))
            if cur is None:
                self._nnz[str(segment)] = [per_row, float(width), 1]
            else:
                cur[0] = (1 - self.ewma) * cur[0] + self.ewma * per_row
                cur[1] = (1 - self.ewma) * cur[1] + self.ewma * float(width)
                cur[2] += 1

    def nnz_bytes(self, segment: str, batch: int) -> Optional[float]:
        """Predicted CSR wire bytes for one ``batch``-row staging of the
        segment's sparse column: values (f32) + indices (i32) per nonzero
        plus the i32 indptr — bytes ≈ f(nnz), not N x F. None until an
        ``observe_nnz`` has been folded (the roofline's nnz-aware bound
        and the layout decision both gate on it)."""
        if batch <= 0:
            return None
        with self._lock:
            rec = self._nnz.get(str(segment))
        if rec is None:
            return None
        return batch * rec[0] * 8.0 + (batch + 1) * 4.0

    def dense_bytes(self, segment: str, batch: int) -> Optional[float]:
        """Densified staging bytes for the same batch (rows x observed
        width x f32) — the side the CSR prediction must undercut."""
        if batch <= 0:
            return None
        with self._lock:
            rec = self._nnz.get(str(segment))
        if rec is None:
            return None
        return batch * rec[1] * 4.0

    def choose_layout(self, segment: str,
                      margin: float = 0.5) -> Optional[str]:
        """Should the executor stage this segment's sparse columns as CSR
        wire triples? ``"csr"`` when the predicted per-row wire bytes
        (8·nnz/row + indptr) undercut the densified row (width x f32) by
        at least ``margin`` — sparse enough that the transfer and gather
        win is robust to the density EWMA drifting. None (keep densify)
        otherwise, and ALWAYS None until the segment is calibrated AND the
        density term has ``min_obs`` observations: an uncalibrated model
        changes nothing, so cold start stays bitwise-identical."""
        seg = str(segment)
        if not self.calibrated(seg):
            return None
        with self._lock:
            rec = self._nnz.get(seg)
        if rec is None or rec[2] < self.min_obs or rec[1] <= 0:
            return None
        csr_row = rec[0] * 8.0 + 4.0
        dense_row = rec[1] * 4.0
        if csr_row < dense_row * float(margin):
            return "csr"
        return None

    # -- introspection / serialization -----------------------------------
    def host_ms_per_row(self, stage: str) -> Optional[float]:
        with self._lock:
            rec = self._host.get(str(stage))
            return None if rec is None else round(rec[0], 6)

    def segments(self) -> List[str]:
        with self._lock:
            return sorted({s for (s, _) in self._measured} |
                          {s for (s, _) in self._analytic})

    def prediction_error(self) -> Dict[str, Dict[str, Any]]:
        """Analytical-vs-measured error per (segment, bucket) that has
        both: the perf_report "predicted vs measured" table, and the
        honesty check on the analytical form itself."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for (seg, b), rec in sorted(self._measured.items()):
                if rec.n < self.min_obs:
                    continue
                wall = rec.wall_ms()
                bound = self._analytic_ms((seg, b))
                if wall is None:
                    continue
                row: Dict[str, Any] = {"measured_ms": round(wall, 4),
                                       "batches": rec.n}
                if bound is not None and bound > 0:
                    row["analytic_ms"] = round(bound, 6)
                    row["error_ratio"] = round(wall / bound, 4)
                out.setdefault(seg, {})[str(b)] = row
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            measured = {f"{s}:{b}": rec.to_dict()
                        for (s, b), rec in sorted(self._measured.items())}
            host = {k: {"ms_per_row": round(v[0], 6), "n": v[1]}
                    for k, v in sorted(self._host.items())}
            n_analytic = len(self._analytic)
            variants = {f"{s}:{b}:{v}": {"ms": round(rec[0], 6), "n": rec[1]}
                        for (s, b, v), rec in sorted(self._variant.items())}
            nnz = {s: {"nnz_per_row": round(rec[0], 4),
                       "width": round(rec[1], 2), "n": int(rec[2])}
                   for s, rec in sorted(self._nnz.items())}
        segs = self.segments()
        out = {"segments": segs,
               "calibrated": {s: self.calibrated(s) for s in segs},
               "confidence": {s: self.confidence(s) for s in segs},
               "measured": measured, "host_stages": host,
               "analytic_records": n_analytic,
               "peak_source": self.peaks().get("peak_source")}
        if variants:  # key absent when unused: stats payload parity
            out["variant_trials"] = variants
        if nnz:  # key absent when no sparse data seen: payload parity
            out["nnz"] = nnz
        return out

    def to_dict(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "version": 1,
                "ewma": self.ewma, "min_obs": self.min_obs,
                "compile_horizon": self.compile_horizon,
                "measured": {f"{s}\x00{b}": rec.to_dict()
                             for (s, b), rec in self._measured.items()},
                "analytic": {f"{s}\x00{b}": dict(rec)
                             for (s, b), rec in self._analytic.items()},
                "size_hist": {s: {str(n): c for n, c in h.items()}
                              for s, h in self._size_hist.items()},
                "host": {k: list(v) for k, v in self._host.items()},
                "collectives": {op: [list(p) for p in pts]
                                for op, pts in self._collective.items()},
            }
            if self._variant:  # key absent when unused: payload parity
                out["variants"] = {f"{s}\x00{b}\x00{v}": list(rec)
                                   for (s, b, v), rec in
                                   self._variant.items()}
            if self._nnz:  # key absent when no sparse data seen
                out["nnz"] = {s: list(rec)
                              for s, rec in self._nnz.items()}
            return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any],
                  peaks: Optional[Dict[str, Any]] = None
                  ) -> "SegmentCostModel":
        m = cls(peaks=peaks, ewma=float(d.get("ewma", 0.3)),
                min_obs=int(d.get("min_obs", 4)),
                compile_horizon=int(d.get("compile_horizon", 200)))

        def split(key: str) -> Tuple[str, int]:
            seg, b = key.rsplit("\x00", 1)
            return seg, int(b)

        for key, rec in (d.get("measured") or {}).items():
            m._measured[split(key)] = _BucketRecord.from_dict(rec)
        for key, rec in (d.get("analytic") or {}).items():
            m._analytic[split(key)] = {k: float(v) for k, v in rec.items()}
        for seg, hist in (d.get("size_hist") or {}).items():
            m._size_hist[seg] = {int(n): int(c) for n, c in hist.items()}
        for k, v in (d.get("host") or {}).items():
            m._host[k] = [float(v[0]), int(v[1])]
        for op, pts in (d.get("collectives") or {}).items():
            m._collective[op] = [(float(p[0]), float(p[1])) for p in pts]
        for key, rec in (d.get("variants") or {}).items():
            seg, b, vid = key.rsplit("\x00", 2)
            m._variant[(seg, int(b), vid)] = [float(rec[0]), int(rec[1])]
        for seg, rec in (d.get("nnz") or {}).items():
            m._nnz[seg] = [float(rec[0]), float(rec[1]), int(rec[2])]
        return m
