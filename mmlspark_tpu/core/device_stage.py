"""Device-stage contract: how a pipeline stage joins a fused XLA program.

PR 1 measured the flagship featurize path at ~11.5k images/sec per-call but
~260 end-to-end: the stage-BOUNDARY cost (D2H readback, host re-batching,
fresh H2D) dominated, not XLA compute. Operator fusion across stage
boundaries is the standard fix (TVM, arXiv:1802.04799); this module defines
the contract a stage implements to participate:

    stage.device_fn(schema) -> Optional[DeviceFn]

A ``DeviceFn`` describes the stage as a jittable column program plus the
host-side shims the fused executor (core/fusion.py) needs at segment edges:

  - ``fn(params, env)``      the traceable body: reads batched [B, ...]
                             arrays out of ``env`` (a dict keyed by column
                             name), returns the dict of columns it writes.
                             Raise ``FusionUnsupported`` at TRACE time when
                             the incoming shapes/dtypes rule fusion out —
                             the executor falls back to the host path.
  - ``prepare(cols, ctx)``   host per-row prep applied only to SEGMENT-
                             EXTERNAL inputs (struct -> array conversion,
                             decode, host-exact ops like resize whose f64
                             arithmetic cannot be reproduced bitwise on
                             device). MUST reuse the unfused code path so
                             fused == unfused stays bitwise.
  - ``finalize(outs, ctx)``  host per-partition post-processing of the
                             stage's device outputs after readback (rebuild
                             image structs, f64 casts, objective transforms)
                             — again the exact unfused code.

The bitwise contract: everything placed in ``fn`` must be provably exact
between the host numpy implementation and XLA — value-preserving moves
(crop/flip/transpose/concat), exact casts (uint8 -> f32), identical
elementwise IEEE arithmetic, or literally the same traced jaxpr (NN
forwards, the GBDT forest kernels). Anything else belongs in ``prepare``/
``finalize`` where the unfused host code runs unchanged.

``CompileCache`` is the shared executable cache for fused segments, keyed by
(segment identity, bucketed batch shape, dtypes) with hit/miss/compile-time
counters — the per-shape cost visibility of the TPU performance-model work
(arXiv:2008.01040) applied to fused programs.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple


class FusionUnsupported(Exception):
    """A stage cannot join (or continue) a fused segment for the observed
    schema/shapes/dtypes. Raised at plan or trace time; the executor falls
    back to the unfused host path for the segment, never failing the
    transform."""


@dataclasses.dataclass
class DeviceFn:
    """One stage's slice of a fused device program (see module docstring).

    ``key`` must be hashable and identify the traced computation (stage
    class, column names, op list, model identity...): it keys the shared
    compile cache together with the batch shape signature.

    ``device_outputs``: the env keys the executor reads back for this stage
    (defaults to ``out_cols``); internal keys (prefixed ``__``) let a stage
    compute raw device values that only ``finalize`` consumes — e.g. the
    GBDT forest scores, finalized into probability/prediction columns in
    f64 on host. A stage with internal outputs is ``terminal``: nothing
    downstream can consume its finalized columns on device.

    ``null_policy``: "propagate" = rows with a null input produce null
    outputs (DNN semantics); "fallback" = nulls in this stage's external
    inputs force the segment onto the host path (stages whose host code
    gives nulls a value, e.g. the assembler's NaN fill).
    """

    key: Tuple
    in_cols: Tuple[str, ...]
    out_cols: Tuple[str, ...]
    fn: Callable[[Any, Dict[str, Any]], Dict[str, Any]]
    params: Any = None
    prepare: Optional[Callable] = None
    finalize: Optional[Callable] = None
    device_outputs: Optional[Tuple[str, ...]] = None
    accepts: Optional[Callable] = None   # ({col: probe_row}) -> bool
    null_policy: str = "propagate"
    reject_sparse: bool = True
    drop_invalid: bool = False
    # fn can consume input produced by an upstream device stage in the same
    # segment (False when `prepare` does host work fn cannot replicate —
    # the planner then starts a new segment at this stage)
    internal_ok: bool = True
    terminal: bool = False
    # heavy = worth a device round-trip on its own (NN forward, forest
    # kernel); a segment of only light stages executes on the host path
    heavy: bool = False
    # Optional model/feature-dim sharding declaration for the pod-scale
    # planner (parallel/shardplan.py): {input col: array dim (batch = 0)
    # that may shard over the mesh's tensor axis}. Batch-dim data
    # parallelism needs no declaration (always legal — fn is row
    # independent by contract); a feature-dim candidate is only DERIVED
    # for a segment when every stage declares one for its external inputs.
    shard_dims: Optional[Dict[str, int]] = None
    # --- compiler-search capability flags (docs/compiler_search.md) ------
    # stitchable: this TERMINAL stage's host finalize shim is transpiled
    # (device_finalize below), so the planner may keep the segment OPEN
    # across it — downstream device stages keep consuming the segment's
    # device-resident columns instead of paying the readback +
    # `rows_to_batch` re-batch + H2D round-trip a terminal close costs —
    # when the stitch knob + calibrated cost model approve. The stage's own
    # finalized columns stay host-only; a later reader of those splits.
    stitchable: bool = False
    # device_finalize: jittable replacement for the numeric part of
    # `finalize` — (params, env) -> extra device outputs (named by
    # `device_finalize_outputs`) traced into the SAME fused program when
    # the stitch knob enables it; `finalize_stitched(outs, ctx)` is the
    # host shim that builds the final columns from those readbacks.
    # `finalize_tolerance` DECLARES the allowed numeric deviation vs the
    # host `finalize` path (None would claim bitwise — the transpiled f64
    # reductions run in f32 on device, so they must declare a tolerance).
    device_finalize: Optional[Callable] = None
    device_finalize_outputs: Tuple[str, ...] = ()
    finalize_stitched: Optional[Callable] = None
    finalize_tolerance: Optional[float] = None
    # --- sparse capability (docs/sparse.md) ------------------------------
    # sparse_cols: input columns this stage can consume as a CSR triple
    # instead of a densified [B, F] matrix. For a capable column ``c`` the
    # executor stages four env keys — ``{c}:indptr`` (i32 [B+1]),
    # ``{c}:indices`` (i32 [nnz_pad]), ``{c}:values`` (f32 [nnz_pad]) and
    # ``{c}:width`` (i32 scalar) — and calls ``sparse_fn`` in place of
    # ``fn``. The CSR path is opt-in per segment (the tuner's journaled
    # ``layout`` knob); with the knob off, a capable stage still takes the
    # densify path, so declaring the capability alone changes nothing.
    sparse_cols: Tuple[str, ...] = ()
    # sparse_fn(params, env): the traceable CSR body — must produce outputs
    # bitwise-equal (or within the kernel's declared tolerance) to ``fn``
    # over the densified equivalent of the same triple.
    sparse_fn: Optional[Callable] = None

    def __post_init__(self):
        self.in_cols = tuple(self.in_cols)
        self.out_cols = tuple(self.out_cols)
        self.sparse_cols = tuple(self.sparse_cols)
        if self.device_outputs is None:
            self.device_outputs = self.out_cols
        else:
            self.device_outputs = tuple(self.device_outputs)
        self.device_finalize_outputs = tuple(self.device_finalize_outputs)


class CompileCache:
    """Shared fused-executable cache with hit/miss/compile-time counters
    and per-(segment, shape-bucket) XLA cost records.

    Key: (segment key, bucketed batch shape+dtype signature). Value: the
    compiled callable. AOT compilation (jit -> lower -> compile) is timed so
    ``compile_time_s`` measures XLA work, not the first batch's compute.

    At miss time the freshly-compiled executable's ``cost_analysis()`` /
    ``memory_analysis()`` are harvested (obs/perf.py ``extract_cost`` —
    getattr-gated, every absence degrades to "no record") and stored under
    the human-readable ``(label, shape)`` pair the caller passes, feeding
    the ``mmlspark_segment_cost_*`` families and the roofline report.

    Concurrency contract: counter updates AND cost capture happen under the
    cache lock in one acquisition, so a concurrent ``stats()`` scrape never
    sees a torn hits/misses/compile_time_s triple. ``reset()`` bumps a
    generation counter; a build that a reset raced still installs its
    (valid) executable but does NOT book its miss/compile-time/cost into
    the post-reset counters — cleared stats never mix epochs.

    Eviction contract: the cache is a bounded LRU — a hit refreshes the
    entry, an insert past ``capacity`` evicts the least-recently-used one
    (a long-running server with many shape buckets previously grew compiled
    executables forever under insertion-order eviction). Evicting an entry
    also drops its harvested cost record, so ``costs()`` only ever
    describes executables that are actually resident; ``evictions`` counts
    drops (exposed as ``mmlspark_segment_cache_evictions_total``).
    ``capacity`` defaults from ``MMLSPARK_SEGMENT_CACHE_CAP`` when unset.

    Persistent tier (serving/fleet/cache.py): ``attach_persistent`` hangs a
    second, cross-process tier under the miss path. A memory miss first
    asks the tier for a deserialized executable (no compile, no
    miss/compile-time accounting — the tier keeps its own hit/miss/error
    counters); only a two-tier miss runs ``builder``, after which the
    fresh executable is offered back to the tier best-effort. With no tier
    attached (the default) every code path and counter is exactly the
    pre-fleet behavior.
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            import os

            try:
                capacity = int(os.environ.get(
                    "MMLSPARK_SEGMENT_CACHE_CAP", "256"))
            except ValueError:
                capacity = 256
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self._capacity = int(capacity)
        self._entries: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()
        self._gen = 0
        self._costs: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # entry key -> its cost-record key, so eviction can drop the record
        self._cost_key: Dict[Tuple, Tuple[str, str]] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.compile_time_s = 0.0
        # optional cross-process second tier (duck-typed: load/store/stats;
        # serving/fleet/cache.py PersistentCompileCache). None = single-tier.
        self._persistent: Optional[Any] = None

    @property
    def capacity(self) -> int:
        return self._capacity

    def attach_persistent(self, tier: Optional[Any]) -> None:
        """Hang a persistent tier under the miss path (None detaches). The
        tier must be exception-free: ``load`` returns ``(fn, cost)`` or
        ``None``; ``store`` is fire-and-forget."""
        with self._lock:
            self._persistent = tier

    @property
    def persistent(self) -> Optional[Any]:
        with self._lock:
            return self._persistent

    def preload(self, key: Tuple, fn: Any, label: Optional[str] = None,
                shape: Optional[str] = None,
                cost: Optional[Dict[str, Any]] = None) -> bool:
        """Install a deserialized executable WITHOUT miss/compile-time
        accounting — the persistent tier's pod-start AOT warm path. Returns
        False when the key is already resident (warm never clobbers a live
        entry)."""
        with self._lock:
            if key in self._entries:
                return False
            while len(self._entries) >= self._capacity:
                self._evict_lru_locked()
                self.evictions += 1
            self._entries[key] = fn
            if label is not None:
                self._costs[(str(label), str(shape))] = dict(cost or {})
                self._cost_key[key] = (str(label), str(shape))
            return True

    def set_capacity(self, capacity: int) -> None:
        """Re-bound the cache; shrinking evicts LRU entries immediately."""
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        with self._lock:
            self._capacity = int(capacity)
            while len(self._entries) > self._capacity:
                self._evict_lru_locked()
                self.evictions += 1

    def _evict_lru_locked(self) -> None:
        """Drop the least-recently-used entry (dict order = LRU order:
        hits re-insert at the end) and its cost record. Lock held; the
        caller books ``evictions`` under the same acquisition."""
        key = next(iter(self._entries))
        self._entries.pop(key)
        ck = self._cost_key.pop(key, None)
        if ck is not None:
            self._costs.pop(ck, None)

    def get(self, key: Tuple, builder: Callable[[], Any],
            label: Optional[str] = None,
            shape: Optional[str] = None) -> Any:
        with self._lock:
            if key in self._entries:
                self.hits += 1
                # LRU refresh: move to the end of the dict's order
                fn = self._entries.pop(key)
                self._entries[key] = fn
                return fn
            gen = self._gen
            tier = self._persistent
        if tier is not None:
            # second-tier probe OUTSIDE the lock (deserializing an AOT
            # executable does real I/O). A tier hit installs with NO
            # miss/compile accounting: nothing compiled.
            loaded = tier.load(key, label=label, shape=shape)
            if loaded is not None:
                fn, pcost = loaded
                with self._lock:
                    if key not in self._entries:
                        while len(self._entries) >= self._capacity:
                            self._evict_lru_locked()
                            self.evictions += 1
                        self._entries[key] = fn
                        if self._gen == gen and label is not None:
                            self._costs[(str(label), str(shape))] = dict(
                                pcost or {})
                            self._cost_key[key] = (str(label), str(shape))
                    return self._entries[key]
        # build OUTSIDE the lock: XLA compiles can take seconds and other
        # segments/threads must not serialize behind them
        t0 = time.perf_counter()
        fn = builder()
        dt = time.perf_counter() - t0
        cost = None
        if label is not None:
            from ..obs.perf import extract_cost

            cost = extract_cost(fn)
        rec = dict(cost or {})
        rec["compile_s"] = round(dt, 6)
        with self._lock:
            stale = self._gen != gen  # reset() raced the build
            if not stale:
                self.misses += 1
                self.compile_time_s += dt
                if label is not None:
                    self._costs[(str(label), str(shape))] = dict(rec)
            if key not in self._entries:
                while len(self._entries) >= self._capacity:
                    self._evict_lru_locked()
                    self.evictions += 1
                self._entries[key] = fn
                if not stale and label is not None:
                    self._cost_key[key] = (str(label), str(shape))
            out = self._entries[key]
        if tier is not None and not stale and out is fn:
            # offer the fresh executable to the persistent tier, outside
            # every lock: store is best-effort and must never block or
            # fail the serving path (the tier swallows its own errors)
            tier.store(key, fn, cost=rec, label=label, shape=shape)
        return out

    def clear(self) -> None:
        with self._lock:
            self._gen += 1
            self._entries.clear()
            self._costs.clear()
            self._cost_key.clear()
            self.hits = 0
            self.misses = 0
            self.evictions = 0
            self.compile_time_s = 0.0

    #: reset() is clear() — the name the obs layer documents
    reset = clear

    @property
    def entries(self) -> int:
        with self._lock:
            return len(self._entries)

    def costs(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """{segment label: {shape bucket: cost record}} — flops /
        bytes_accessed / peak_memory_bytes / compile_s per compiled
        executable (whatever subset the backend reported)."""
        with self._lock:
            out: Dict[str, Dict[str, Dict[str, Any]]] = {}
            for (label, shape), rec in self._costs.items():
                out.setdefault(label, {})[shape] = dict(rec)
            return out

    def segment_cost(self, label: str) -> Optional[Dict[str, float]]:
        """Mean per-batch cost across this segment's compiled shape buckets
        (span attrs + quick attribution), or None when nothing recorded."""
        with self._lock:
            recs = [r for (lab, _), r in self._costs.items() if lab == label]
        if not recs:
            return None
        out: Dict[str, float] = {"shape_buckets": float(len(recs))}
        for k in ("flops", "bytes_accessed", "peak_memory_bytes"):
            vals = [r[k] for r in recs if isinstance(r.get(k), (int, float))]
            if vals:
                out[k] = sum(vals) / len(vals)
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            out = {
                "entries": len(self._entries),
                "capacity": self._capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": round(self.hits / total, 4) if total else None,
                "compile_time_s": round(self.compile_time_s, 6),
            }
            tier = self._persistent
        if tier is not None:
            # tier stats OUTSIDE the cache lock (the tier takes its own);
            # the key is absent entirely when no tier is attached, so the
            # fleet=False stats payload is byte-identical to pre-fleet
            try:
                out["persistent"] = tier.stats()
            except Exception as e:  # noqa: BLE001 — stats must not raise
                out["persistent"] = {"error": str(e)}
        return out


_GLOBAL_CACHE = CompileCache()


def compile_cache() -> CompileCache:
    """The process-wide fused-executable cache (shared across pipelines and
    the serving loop, so warm executables survive re-planning)."""
    return _GLOBAL_CACHE
