"""Core utilities: timing, retries, async buffering, resource management.

Parity targets:
  - StopWatch                — core/utils/StopWatch.scala (VW phase timing)
  - retry_with_timeout/retry — downloader/ModelDownloader FaultToleranceUtils.retryWithTimeout
                               (ModelDownloader.scala:37-47) and LightGBM networkInit
                               exponential backoff (TrainUtils.scala:365-381)
  - buffered_await           — core/utils/AsyncUtils.bufferedAwait
  - using                    — core/env/StreamUtilities.using
  - cast_utilities           — core/utils/CastUtilities
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import logging
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence, TypeVar

import numpy as np

T = TypeVar("T")

log = logging.getLogger("mmlspark_tpu")


class StopWatch:
    """Cumulative nanosecond timer (reference core/utils/StopWatch.scala)."""

    def __init__(self):
        self.elapsed_ns = 0
        self._start: Optional[int] = None

    def start(self) -> None:
        self._start = time.perf_counter_ns()

    def stop(self) -> None:
        if self._start is not None:
            self.elapsed_ns += time.perf_counter_ns() - self._start
            self._start = None

    @contextlib.contextmanager
    def measure(self) -> Iterator[None]:
        self.start()
        try:
            yield
        finally:
            self.stop()

    @property
    def elapsed_s(self) -> float:
        return self.elapsed_ns / 1e9


def retry(fn: Callable[[], T], max_retries: int = 3, initial_delay_s: float = 0.1,
          backoff: float = 2.0, exceptions=(Exception,),
          on_retry: Optional[Callable[[int, Exception], None]] = None) -> T:
    """Exponential-backoff retry (LightGBM networkInit parity, TrainUtils.scala:365-381)."""
    delay = initial_delay_s
    for attempt in range(max_retries):
        try:
            return fn()
        except exceptions as e:  # noqa: PERF203
            if attempt == max_retries - 1:
                raise
            if on_retry:
                on_retry(attempt, e)
            log.warning("retry %d/%d after %s: %s", attempt + 1, max_retries, type(e).__name__, e)
            time.sleep(delay)
            delay *= backoff
    raise RuntimeError("unreachable")


def retry_with_timeout(fn: Callable[[], T], timeout_s: float, max_retries: int = 3) -> T:
    """Run ``fn`` with a per-attempt timeout (ModelDownloader.scala:37-47 parity)."""
    last_err: Optional[Exception] = None
    for _ in range(max_retries):
        pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
        fut = pool.submit(fn)
        try:
            return fut.result(timeout=timeout_s)
        except Exception as e:  # includes TimeoutError
            last_err = e
        finally:
            # Don't join a potentially-hung worker: a blocking shutdown would defeat
            # the timeout. The daemon thread is abandoned on timeout.
            pool.shutdown(wait=False, cancel_futures=True)
    raise last_err  # type: ignore[misc]


def buffered_await(futures: Iterable[concurrent.futures.Future], buffer_size: int
                   ) -> Iterator[Any]:
    """Yield future results in order while keeping at most ``buffer_size`` outstanding
    (reference core/utils/AsyncUtils.bufferedAwait — bounded pipelined concurrency)."""
    window: List[concurrent.futures.Future] = []
    it = iter(futures)
    try:
        for _ in range(buffer_size):
            window.append(next(it))
    except StopIteration:
        pass
    while window:
        head = window.pop(0)
        yield head.result()
        try:
            window.append(next(it))
        except StopIteration:
            continue


@contextlib.contextmanager
def using(*resources):
    """Resource-safe block (core/env/StreamUtilities.using parity)."""
    try:
        yield resources if len(resources) > 1 else resources[0]
    finally:
        for r in resources:
            close = getattr(r, "close", None)
            if close:
                with contextlib.suppress(Exception):
                    close()


def cast_column(col: np.ndarray, dtype: str) -> np.ndarray:
    """Numeric column coercion (core/utils/CastUtilities parity)."""
    if col.dtype == object:
        return np.array([np.asarray(v, dtype=dtype) if isinstance(v, np.ndarray)
                         else dtype_scalar(v, dtype) for v in col], dtype=object)
    return col.astype(dtype)


def dtype_scalar(v: Any, dtype: str) -> Any:
    return np.dtype(dtype).type(v)


class SharedVariable:
    """Per-process lazily-initialized singleton (io/http/SharedVariable.scala:1-65 parity).

    In the reference this provides one HTTP client / native handle per JVM shared across
    partitions; here, one per host process shared across partition map calls.
    """

    _instances: dict = {}
    _UNSET = object()

    def __init__(self, factory: Callable[[], T], key: Optional[str] = None):
        self._factory = factory
        self._key = key  # None => cache on this instance (keys from id() would be reused)
        self._value: Any = SharedVariable._UNSET

    def get(self) -> T:
        if self._key is None:
            if self._value is SharedVariable._UNSET:
                self._value = self._factory()
            return self._value
        if self._key not in SharedVariable._instances:
            SharedVariable._instances[self._key] = self._factory()
        return SharedVariable._instances[self._key]

    @classmethod
    def clear_all(cls) -> None:
        cls._instances.clear()
