from .dataframe import DataFrame
from .faults import (
    DEADLINE_HEADER, Deadline, FaultInjector, InjectedFault, RetryPolicy,
    deadline_from_headers,
)
from .params import Param, Params, ComplexParam, ServiceParam
from .pipeline import (
    Estimator, Evaluator, Model, Pipeline, PipelineModel, PipelineStage, Transformer,
)
from .profiling import annotate, device_memory_stats, profile_transform, trace
from .schema import ColType, ImageSchema, Schema
