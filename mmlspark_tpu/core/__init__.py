from .dataframe import DataFrame
from .params import Param, Params, ComplexParam, ServiceParam
from .pipeline import (
    Estimator, Evaluator, Model, Pipeline, PipelineModel, PipelineStage, Transformer,
)
from .schema import ColType, ImageSchema, Schema
