"""Column dtypes and structured schemas for the columnar DataFrame.

Re-designs the reference's Spark schema layer (core/schema/SparkBindings.scala:13-47,
core/schema/ImageSchemaUtils.scala, core/schema/Categoricals.scala) for a numpy/Arrow
columnar substrate:

  - ``ColType``     — logical column types (scalar, vector, tensor, struct, binary, string).
  - ``ImageSchema`` — the image struct layout (path, height, width, channels, mode, data),
    matching Spark's ImageSchema that ImageTransformer/UnrollImage consume.
  - ``Binding``     — dataclass <-> column-struct codec (SparkBindings parity) so typed
    request/response records (HTTP, cognitive services) round-trip through columns.
  - categorical metadata helpers (CategoricalUtilities parity): per-column level maps
    carried in DataFrame metadata instead of Spark column metadata.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, fields, is_dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np


class ColType:
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    INT32 = "int32"
    INT64 = "int64"
    BOOL = "bool"
    STRING = "string"
    BINARY = "binary"
    VECTOR = "vector"      # 1-D float array per row (ragged allowed; object-backed)
    TENSOR = "tensor"      # n-D array per row
    STRUCT = "struct"      # dict per row
    OBJECT = "object"      # anything else

    NUMERIC = (FLOAT32, FLOAT64, INT32, INT64, BOOL)


def infer_coltype(col: np.ndarray) -> str:
    """Infer the logical type of a column (a numpy array of per-row values)."""
    if col.dtype == np.float32:
        return ColType.FLOAT32
    if col.dtype == np.float64:
        return ColType.FLOAT64
    if col.dtype in (np.int32,):
        return ColType.INT32
    if col.dtype in (np.int64,):
        return ColType.INT64
    if col.dtype == np.bool_:
        return ColType.BOOL
    if col.dtype.kind in ("U", "S"):
        return ColType.STRING
    if col.dtype == object:
        for v in col:
            if v is None:
                continue
            if isinstance(v, str):
                return ColType.STRING
            if isinstance(v, (bytes, bytearray)):
                return ColType.BINARY
            if isinstance(v, np.ndarray):
                return ColType.VECTOR if v.ndim == 1 else ColType.TENSOR
            if isinstance(v, dict):
                return ColType.STRUCT
            if isinstance(v, (float, int)):
                return ColType.FLOAT64
            return ColType.OBJECT
        return ColType.OBJECT
    if col.ndim > 1:
        return ColType.VECTOR if col.ndim == 2 else ColType.TENSOR
    return ColType.OBJECT


@dataclass
class Schema:
    """Ordered mapping of column name -> logical type, plus per-column metadata."""

    types: Dict[str, str]
    metadata: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)

    @property
    def names(self) -> List[str]:
        return list(self.types)

    def __contains__(self, name: str) -> bool:
        return name in self.types

    def __getitem__(self, name: str) -> str:
        return self.types[name]

    def require(self, name: str, *allowed: str) -> None:
        if name not in self.types:
            raise KeyError(f"Column '{name}' not found; have {self.names}")
        if allowed and self.types[name] not in allowed:
            raise TypeError(
                f"Column '{name}' has type {self.types[name]}, expected one of {allowed}")

    def meta(self, name: str) -> Dict[str, Any]:
        return self.metadata.setdefault(name, {})

    def copy(self) -> "Schema":
        import copy as _c
        return Schema(dict(self.types), _c.deepcopy(self.metadata))


def find_unused_column_name(prefix: str, schema: "Schema | Sequence[str]") -> str:
    """Reference core/schema/DatasetExtensions.findUnusedColumnName."""
    names = set(schema.names if isinstance(schema, Schema) else schema)
    name, i = prefix, 0
    while name in names:
        i += 1
        name = f"{prefix}_{i}"
    return name


# ---------------------------------------------------------------------------
# Image schema (Spark ImageSchema parity; consumed by image stages)
# ---------------------------------------------------------------------------

class ImageSchema:
    """Row layout for decoded images, as a per-row dict (STRUCT column).

    Fields mirror Spark's ImageSchema struct that the reference's image stages read
    (core/schema/ImageSchemaUtils.scala, opencv/ImageTransformer.scala:26-150):
    origin, height, width, nChannels, mode, data. ``data`` here is an HWC uint8
    (or float32) numpy array instead of flattened BGR bytes — TPU-friendlier, and
    converters handle the flat-bytes form at IO boundaries.
    """

    FIELDS = ("origin", "height", "width", "nChannels", "mode", "data")

    OCV_8UC1 = 0
    OCV_8UC3 = 16
    OCV_8UC4 = 24
    UNDEFINED = -1

    @staticmethod
    def make(data: np.ndarray, origin: str = "") -> Dict[str, Any]:
        if data.ndim == 2:
            data = data[:, :, None]
        h, w, c = data.shape
        mode = {1: ImageSchema.OCV_8UC1, 3: ImageSchema.OCV_8UC3,
                4: ImageSchema.OCV_8UC4}.get(c, ImageSchema.UNDEFINED)
        return {"origin": origin, "height": int(h), "width": int(w),
                "nChannels": int(c), "mode": mode, "data": data}

    @staticmethod
    def is_image(value: Any) -> bool:
        return isinstance(value, dict) and set(ImageSchema.FIELDS) <= set(value)

    @staticmethod
    def to_array(row: Dict[str, Any]) -> np.ndarray:
        d = row["data"]
        if isinstance(d, (bytes, bytearray)):
            arr = np.frombuffer(bytes(d), dtype=np.uint8)
            return arr.reshape(row["height"], row["width"], row["nChannels"])
        return np.asarray(d)


# ---------------------------------------------------------------------------
# Dataclass <-> columns codec (SparkBindings parity)
# ---------------------------------------------------------------------------

class Binding:
    """Typed record <-> STRUCT-column codec.

    Reference: core/schema/SparkBindings.scala:13-47 generates Row<->case-class codecs
    from ExpressionEncoders; here dataclasses play the case-class role and rows are
    per-element dicts in an object column.
    """

    @staticmethod
    def to_row(obj: Any) -> Any:
        if obj is None or isinstance(obj, (str, bytes, int, float, bool, np.ndarray)):
            return obj
        if is_dataclass(obj):
            return {f.name: Binding.to_row(getattr(obj, f.name)) for f in fields(obj)}
        if isinstance(obj, (list, tuple)):
            return [Binding.to_row(v) for v in obj]
        if isinstance(obj, dict):
            return {k: Binding.to_row(v) for k, v in obj.items()}
        return obj

    @staticmethod
    def from_row(cls: Type, row: Any) -> Any:
        if row is None:
            return None
        if is_dataclass(cls):
            kwargs = {}
            hints = {f.name: f.type for f in fields(cls)}
            for f in fields(cls):
                v = row.get(f.name) if isinstance(row, dict) else getattr(row, f.name, None)
                kwargs[f.name] = Binding._coerce_field(hints[f.name], v)
            return cls(**kwargs)
        return row

    @staticmethod
    def _coerce_field(hint: Any, v: Any) -> Any:
        if v is None:
            return None
        origin = getattr(hint, "__origin__", None)
        if origin in (list, List):
            (inner,) = hint.__args__
            return [Binding.from_row(inner, x) if is_dataclass(inner) else x for x in v]
        if is_dataclass(hint) if isinstance(hint, type) else False:
            return Binding.from_row(hint, v)
        return v


# ---------------------------------------------------------------------------
# Categorical metadata (Categoricals.scala parity)
# ---------------------------------------------------------------------------

def set_categorical_levels(schema: Schema, col: str, levels: Sequence[Any]) -> None:
    schema.meta(col)["categorical_levels"] = list(levels)


def get_categorical_levels(schema: Schema, col: str) -> Optional[List[Any]]:
    return schema.metadata.get(col, {}).get("categorical_levels")
