"""Profiling: JAX/XLA profiler integration on top of the StopWatch layer.

The reference's tracing story is wall-clock instrumentation (StopWatch.scala,
Timer.scala) because Spark owns the deeper profile. On TPU the deeper profile
is the XLA one — per-op device timelines, HBM traffic, MXU utilization — so
this module wires ``jax.profiler`` into the framework idioms:

  - ``trace(log_dir)``: context manager capturing a TensorBoard/Perfetto
    trace of everything inside it (device + host).
  - ``annotate(name)``: named span inside a trace, so stage boundaries are
    visible between XLA ops (wraps ``jax.profiler.TraceAnnotation``).
  - ``profile_transform(stage, df, log_dir)``: one-call stage profile —
    runs ``stage.transform(df)`` under a trace with a named span per call.
  - ``device_memory_stats()``: per-device live/peak HBM bytes, the quick
    "am I about to OOM" check (jax.local_devices()[i].memory_stats()).

Traces open in TensorBoard (`tensorboard --logdir <dir>`) or Perfetto; on
TPU they include the hardware trace, on CPU the host timeline only.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Iterator, List, Optional

from .utils import StopWatch, log


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_trace: bool = False) -> Iterator[None]:
    """Capture a JAX profiler trace of the enclosed block into ``log_dir``."""
    import jax

    with jax.profiler.trace(log_dir,
                            create_perfetto_trace=create_perfetto_trace):
        yield
    log.info("profiler trace written to %s", log_dir)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span (shows up between XLA ops in the trace timeline)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


def profile_transform(stage, df, log_dir: str, iterations: int = 1,
                      create_perfetto_trace: bool = False) -> Dict[str, Any]:
    """Profile ``stage.transform(df)``: wall clock via StopWatch + a full
    XLA trace in ``log_dir``. Returns {"elapsed_s", "per_call_s", "log_dir"}.
    """
    if iterations < 1:
        raise ValueError(f"iterations must be >= 1, got {iterations}")
    watch = StopWatch()
    name = type(stage).__name__
    with trace(log_dir, create_perfetto_trace=create_perfetto_trace):
        for i in range(iterations):
            with annotate(f"{name}.transform[{i}]"), watch.measure():
                stage.transform(df)
    return {"elapsed_s": watch.elapsed_s,
            "per_call_s": watch.elapsed_s / iterations,
            "log_dir": log_dir}


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device memory stats (bytes_in_use / peak_bytes_in_use / limit when
    the backend reports them; CPU backends may report nothing)."""
    import jax

    out: List[Dict[str, Any]] = []
    for d in jax.local_devices():
        stats: Optional[Dict[str, Any]] = None
        try:
            stats = d.memory_stats()
        except Exception:  # backend without memory stats
            stats = None
        out.append({"device": str(d), "platform": d.platform,
                    "stats": stats or {}})
    return out
