"""Static-shape minibatching: the ragged-rows -> XLA bridge.

The reference batches rows for native eval via MiniBatchTransformer/Batchers
(stages/MiniBatchTransformer.scala:14-200, stages/Batchers.scala:12-160). On TPU this
layer is *the* cross-cutting design problem (SURVEY §7 hard part #2): XLA wants static
shapes, rows are ragged. Strategy:

  - ``pad_to_bucket``: round batch size up to a bucket (powers of two by default) so jit
    recompiles O(log n) times, not O(n); excess rows masked out.
  - ``Minibatcher``: slice a column dict into fixed-size padded device batches + mask.
  - ``unbatch``: concatenate per-batch outputs and strip padding (FlattenBatch parity).

All stages that touch devices go through this module, so padding/bucketing policy is
defined once.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

Partition = Dict[str, np.ndarray]


def _release_staging(item) -> None:
    """Return a dropped batch's SlotPool lease (``Batch.staging``) to the
    pool. Queued batches can carry leased staging buffers; dropping one on
    close()/abort without releasing would permanently shrink the shared,
    never-replenished pool. Idempotent (SlotLease.release guards), no-op
    for lease-less items."""
    lease = getattr(item, "staging", None)
    if lease is not None:
        try:
            lease.release()
        except Exception:  # noqa: BLE001 - cleanup must not mask the abort
            pass


class DevicePrefetcher:
    """Background-thread device prefetch: pull items from an iterator, ship
    them to the device (``put``), and hand over device-resident results
    through a bounded queue — the producer's decode/stack/H2D cost overlaps
    the consumer's compute.

    Reference analogue: the background-thread DynamicBufferedBatcher
    (stages/Batchers.scala:12-160) that keeps Spark partitions fed while the
    consumer works. ``depth`` bounds in-flight batches (double buffering by
    default) so memory stays bounded.

    Iterate it like the original iterator; producer exceptions re-raise at
    the consumer.
    """

    _DONE = object()

    def __init__(self, it: Iterator, put: Optional[Callable] = None,
                 depth: int = 2):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._err: List[BaseException] = []
        self._stop = threading.Event()

        def offer(item) -> bool:
            """Bounded put that gives up when the consumer closed — an
            abandoned iteration must not strand this thread (and its
            device-resident buffers) on a full queue forever."""
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in it:
                    if self._stop.is_set():
                        _release_staging(item)
                        return
                    staged = put(item) if put is not None else item
                    if not offer(staged):
                        _release_staging(staged)
                        return
            except BaseException as e:  # noqa: BLE001 - re-raised at consumer
                self._err.append(e)
            finally:
                offer(self._DONE)

        self._thread = threading.Thread(target=produce, daemon=True,
                                        name="device-prefetch")
        self._thread.start()

    def close(self) -> None:
        """Release the producer thread and any queued buffers (idempotent).
        Dropped items hand their staging leases back to the SlotPool — an
        early abort must not strand pre-allocated slot buffers."""
        self._stop.set()
        try:
            while True:
                _release_staging(self._q.get_nowait())
        except Exception:
            pass

    def _get(self):
        """Blocking get that an external close() can always interrupt: poll
        with a timeout and re-check _stop, so a consumer is never stranded
        on an empty queue whose producer already gave up (the DONE injection
        can lose the race with close()'s drain)."""
        import queue

        while True:
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return self._DONE

    def __iter__(self):
        try:
            while True:
                item = self._get()
                if item is self._DONE:
                    if self._err:
                        raise self._err[0]
                    return
                yield item
        finally:
            self.close()


class DynamicBufferedBatcher:
    """Background-thread buffered batcher over any iterator: a producer
    thread fills a bounded buffer (backpressure — it blocks at
    ``max_buffer`` items); each ``next()`` drains EVERYTHING currently
    buffered into one list, so batch size adapts to the consumer's speed
    (slow consumer -> bigger batches, fast consumer -> batches of 1).

    Reference parity: DynamicBufferedBatcher (stages/Batchers.scala:12-60)
    — the iterator primitive under DynamicMiniBatchTransformer. Producer
    exceptions re-raise at the consumer; ``close()`` releases the thread.
    """

    _DONE = object()

    def __init__(self, it: Iterator, max_buffer: int = 1000):
        import queue
        import threading

        if max_buffer <= 0:
            raise ValueError("max_buffer must be positive")
        self._q: "queue.Queue" = queue.Queue(maxsize=max_buffer)
        self._err: List[BaseException] = []
        self._stop = threading.Event()

        def offer(item) -> bool:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in it:
                    if self._stop.is_set() or not offer(item):
                        return
            except BaseException as e:  # noqa: BLE001 - re-raised at consumer
                self._err.append(e)
            finally:
                offer(self._DONE)

        self._thread = threading.Thread(target=produce, daemon=True,
                                        name="dynamic-batcher")
        self._thread.start()

    def close(self) -> None:
        import queue

        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except Exception:
            pass
        # wake any consumer blocked in a no-timeout get(): the producer's
        # offer(_DONE) gives up once _stop is set, so DONE must be fed from
        # here (the drain above guarantees space; a racing put is fine to
        # drop — the consumer only needs one)
        try:
            self._q.put_nowait(self._DONE)
        except queue.Full:
            pass

    def _get(self):
        """Blocking get interruptible by an external close(): poll with a
        timeout and re-check _stop (close()'s drain can race a blocked
        producer put and lose the injected DONE on a re-filled queue)."""
        import queue

        while True:
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set():
                    return self._DONE

    def __iter__(self):
        import queue

        try:
            done = False
            while not done:
                batch = [self._get()]  # block for at least one item
                try:
                    while True:
                        batch.append(self._q.get_nowait())
                except queue.Empty:
                    pass
                # scan the WHOLE batch for the sentinel: a producer blocked in
                # put() when close() drained can land items AFTER the injected
                # DONE, so it is not necessarily last — anything behind it is
                # abandoned by close() semantics, and the opaque sentinel must
                # never leak to the consumer as data
                for i, item in enumerate(batch):
                    if item is self._DONE:
                        batch = batch[:i]
                        done = True
                        break
                if batch:
                    yield batch
            if self._err:
                raise self._err[0]
        finally:
            self.close()


class TimeIntervalBatcher:
    """Time-windowed batcher over any iterator: a producer thread buffers
    items; batches flush every ``interval_s`` seconds (whatever arrived in
    the window, >= 1 item) or at ``max_batch_size``, whichever first.

    Reference parity: TimeIntervalMiniBatchTransformer's iterator
    (stages/Batchers.scala:98-160). Windows with no items yield nothing
    (the reference blocks for the first element too).
    """

    _DONE = object()

    def __init__(self, it: Iterator, interval_s: float = 1.0,
                 max_batch_size: int = int(1e9), max_buffer: int = 1000):
        self._interval = float(interval_s)
        self._max_batch = int(max_batch_size)
        self._inner = DynamicBufferedBatcher(it, max_buffer)

    def close(self) -> None:
        self._inner.close()

    def __iter__(self):
        import queue
        import time as _time

        q, done_tok = self._inner._q, self._inner._DONE
        try:
            done = False
            while not done:
                # _get: interruptible by close() (returns DONE once stopped)
                batch = [self._inner._get()]  # block for the first element
                if batch[0] is done_tok:
                    break
                deadline = _time.monotonic() + self._interval
                while len(batch) < self._max_batch:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        item = q.get(timeout=remaining)
                    except queue.Empty:
                        break
                    if item is done_tok:
                        done = True
                        break
                    batch.append(item)
                if batch:
                    yield batch
            if self._inner._err:
                raise self._inner._err[0]
        finally:
            self.close()


def next_bucket(n: int, buckets: Optional[Sequence[int]] = None, multiple: int = 8) -> int:
    """Smallest allowed static size >= n. Default: next power of two >= max(n, multiple).

    ``buckets`` (an ascending bucket SET) overrides the power-of-two policy:
    this is the knob the cost-model auto-tuner turns (core/costmodel.py
    ``choose_buckets`` picks a set minimizing measured pad-waste + compile
    amortization; callers pass it through ``bucket_policy``/``buckets``
    params). No ``buckets`` = the unchanged static default, so an
    uncalibrated tuner leaves behavior bitwise-identical.
    """
    if n <= 0:
        return multiple
    if buckets:
        for b in buckets:
            if b >= n:
                return b
        return buckets[-1]
    return max(multiple, 1 << (n - 1).bit_length())


def pad_batch(arr: np.ndarray, target: int, pad_value: float = 0.0) -> np.ndarray:
    """Pad leading dim of ``arr`` up to ``target`` rows by repeating zeros."""
    n = arr.shape[0]
    if n == target:
        return arr
    if n > target:
        raise ValueError(f"batch of {n} rows exceeds target {target}")
    pad_width = [(0, target - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=pad_value)


def is_sparse_row(v) -> bool:
    """True for the framework's sparse-row struct ``{"indices", "values"
    [, "size"]}`` (shared by TextFeaturizer and the VW featurizer)."""
    return isinstance(v, dict) and "indices" in v and "values" in v


def sparse_width(col) -> int:
    """The dense width of a sparse-row column: the declared ``size`` when the
    producer carries one (both in-repo producers do — widths then do NOT
    depend on which rows a partition happens to hold), else max index + 1."""
    width = 0
    for v in col:
        if v is None:
            continue
        s = int(v.get("size", 0))
        if not s:
            idx = np.asarray(v["indices"])
            s = int(idx.max()) + 1 if idx.size else 0
        width = max(width, s)
    return width


def densify_sparse(col, width: int, dtype=np.float64) -> np.ndarray:
    """Sparse-row column -> dense [N, width]. Indices >= width are dropped
    (VW masking semantics; also what a narrower fit-time width means)."""
    out = np.zeros((len(col), width), dtype=dtype)
    for i, v in enumerate(col):
        if v is None:
            continue
        idx = np.asarray(v["indices"], dtype=np.int64)
        keep = idx < width
        out[i, idx[keep]] = np.asarray(v["values"], dtype=dtype)[keep]
    return out


def stack_rows(col: np.ndarray, dtype=np.float32) -> np.ndarray:
    """Stack a column of per-row arrays/scalars into one dense [N, ...] array.

    Sparse rows densify here (via ``sparse_width``/``densify_sparse``), so
    every dense consumer (GBDT, DNN, LIME) accepts sparse feature columns the
    way Spark ML estimators accept SparseVector. Ragged dense rows are an
    error — resize/pad upstream (images are resized before unroll in the
    reference too, image/ImageFeaturizer.scala:141-165).
    """
    if col.dtype != object:
        return np.ascontiguousarray(col, dtype=dtype)
    probe = next((v for v in col if v is not None), None)
    if is_sparse_row(probe):
        width = sparse_width(col)
        if width > (1 << 22):
            raise ValueError(
                f"sparse column width {width} is too large to densify — "
                f"use a smaller feature space (e.g. VowpalWabbitFeaturizer"
                f"(numBits<=22), TextFeaturizer(numFeatures<=4194304)) or a "
                f"sparse-native consumer (the VW stages)")
        return densify_sparse(col, width, dtype=dtype)
    rows = [np.asarray(v, dtype=dtype) for v in col]
    shapes = {r.shape for r in rows}
    if len(shapes) > 1:
        raise ValueError(f"Ragged rows (shapes {shapes}); resize/pad before batching")
    return np.stack(rows)


@dataclasses.dataclass
class Batch:
    """One padded, static-shape batch: arrays + validity mask.

    ``staging``: the SlotPool lease (parallel/ingest.py SlotLease) when the
    arrays live in a pre-allocated staging slot — ``timed_stage`` returns
    the buffers to the pool once the batch is device-resident. None for
    plainly-allocated batches (bitwise-identical legacy path)."""

    arrays: Dict[str, np.ndarray]
    mask: np.ndarray          # [B] bool, True = real row
    num_valid: int
    staging: Any = None

    @property
    def size(self) -> int:
        return len(self.mask)


class Minibatcher:
    """FixedMiniBatchTransformer-equivalent over column dicts.

    With ``bucket=True`` the final short batch is padded to a bucket size so compiled
    shapes repeat across partitions; per-row outputs are later cropped by ``num_valid``.
    """

    def __init__(self, batch_size: int = 32, bucket: bool = True,
                 dtype=np.float32, pad_value: float = 0.0,
                 preserve_int: bool = False,
                 buckets: Optional[Sequence[int]] = None,
                 stats=None):
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        self.batch_size = batch_size
        self.bucket = bucket
        self.dtype = dtype
        self.pad_value = pad_value
        # preserve_int: integer columns keep their dtype instead of casting to
        # ``dtype`` — token-id inputs must reach embedding Gathers as ints
        self.preserve_int = preserve_int
        # cost-aware bucket SET (auto-tuner override; None = power-of-two)
        self.buckets = tuple(sorted(buckets)) if buckets else None
        # optional IngestStats receiving per-bucket pad-waste accounting
        self.stats = stats

    def _col_dtype(self, col):
        if not self.preserve_int:
            return self.dtype
        if getattr(col, "dtype", None) is not None and col.dtype != object:
            return None if np.issubdtype(col.dtype, np.integer) else self.dtype
        probe = next((v for v in col if v is not None), None)
        if probe is not None and np.issubdtype(np.asarray(probe).dtype,
                                               np.integer):
            return None
        return self.dtype

    def batches(self, part: Partition, cols: Sequence[str]) -> Iterator[Batch]:
        n = len(next(iter(part.values()))) if part else 0
        dense = {c: stack_rows(part[c], self._col_dtype(part[c]))
                 for c in cols}
        for start in range(0, n, self.batch_size):
            stop = min(start + self.batch_size, n)
            m = stop - start
            target = self.batch_size if (m == self.batch_size or not self.bucket) \
                else next_bucket(m, buckets=self.buckets)
            target = min(target, self.batch_size) if m < self.batch_size else target
            arrays = {c: pad_batch(dense[c][start:stop], target, self.pad_value)
                      for c in cols}
            mask = np.zeros(target, dtype=bool)
            mask[:m] = True
            if self.stats is not None:
                self.stats.note_padding(target, m)
            yield Batch(arrays, mask, m)

    def map_batches(self, part: Partition, cols: Sequence[str],
                    fn: Callable[[Dict[str, np.ndarray]], Any]) -> List[Any]:
        """Apply ``fn`` per padded batch, crop each output's leading dim to num_valid."""
        outs = []
        for b in self.batches(part, cols):
            res = fn(b.arrays)
            outs.append(_crop(res, b.num_valid))
        return outs


def _crop(res: Any, n: int) -> Any:
    if isinstance(res, dict):
        return {k: _crop(v, n) for k, v in res.items()}
    if isinstance(res, (list, tuple)):
        return type(res)(_crop(v, n) for v in res)
    arr = np.asarray(res)
    return arr[:n]


def concat_outputs(outs: List[Any]) -> Any:
    """FlattenBatch parity: merge per-batch outputs back into full-length columns."""
    if not outs:
        return outs
    first = outs[0]
    if isinstance(first, dict):
        return {k: concat_outputs([o[k] for o in outs]) for k in first}
    if isinstance(first, (list, tuple)) and not isinstance(first, np.ndarray):
        return type(first)(concat_outputs([o[i] for o in outs]) for i in range(len(first)))
    return np.concatenate([np.asarray(o) for o in outs], axis=0)


def pad_to_multiple_of_shards(n: int, shards: int) -> int:
    """Rows needed so a global batch splits evenly across data shards."""
    return int(math.ceil(n / shards) * shards)
