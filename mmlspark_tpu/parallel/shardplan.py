"""Sharding planner: cost-model-planned partition specs for fused segments.

Every fused segment (core/fusion.py) compiles for ONE device and serving
replicas are data-parallel over ``jax.local_devices()`` only — the single
biggest untouched scaling axis in ROADMAP. This module opens it, in the
spirit of Automap and "A Learned Performance Model for TPUs" (PAPERS.md):
partition specs are DERIVED from the stage graph and CHOSEN by the cost
model, never hand-annotated.

  - ``candidates(segment, mesh)`` derives the candidate partitionings a
    segment admits: batch-dim data parallelism over the mesh's ``data``
    axis by default (every external input shards its leading dim — always
    legal for the row-independent fused programs the planner builds), plus
    a model/feature-dim candidate over the ``tensor`` axis where every
    DeviceFn in the segment DECLARES a shardable feature dim
    (``DeviceFn.shard_dims``). Candidates are descriptions (no jax import)
    so the Tuner can enumerate them host-side.
  - ``sharding_for(segment, mesh, name)`` resolves a candidate into a
    ``SegmentSharding``: the ``NamedSharding``s for inputs/params/outputs
    (built over ``make_mesh()`` meshes via the parallel/mesh.py helpers —
    the jax 0.4.37 compat gates J001 enforces), the pjit kwargs with
    ``donate_argnums`` on the ring-staged inputs, and the sharded
    ``device_put`` the executor stages batches through.
  - ``measure_collectives(mesh)`` times real all-reduce / all-gather
    probes over the mesh (via ``shard_map_compat``) and feeds the cost
    model's α·bytes collective term — ``choose_sharding`` then prices a
    candidate as flops/shards + α·bytes and becomes a journaled,
    one-step-rollback Tuner knob (core/tune.py).
  - ``shard_groups(mesh)`` / ``submesh_excluding(mesh, devices)`` /
    ``MeshSupervision`` make the PR 10 supervisor mesh-aware: a wedged
    chip quarantines its SHARD GROUP (every device sharing its data-axis
    slice — the model-parallel group it computes with), and the fused
    model re-plans onto the surviving submesh.

Unsharded stays bitwise-identical: with no mesh set (or a 1-shard
candidate) ``sharding_for`` returns None and the executor runs the exact
PR 13 code path — enforced by tests/test_sharding.py.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import faults
from .mesh import (DATA_AXIS, TENSOR_AXIS, MeshSpec, data_sharding,
                   make_mesh, replicated_sharding, shard_map_compat)

__all__ = ["ShardCandidate", "SegmentSharding", "MeshSupervision",
           "candidates", "sharding_for", "tuner_candidates",
           "measure_collectives", "shard_groups", "group_of",
           "submesh_excluding", "donation_supported", "mesh_topology",
           "split_csr_rows", "ragged_allgather_bytes"]

#: candidate partitioning names (the values of the ``sharding`` tuner knob)
SPEC_DATA = "data"
SPEC_FEATURE = "feature"
SPEC_CSR_ROW = "csr_row"


# ---------------------------------------------------------------------------
# Candidate derivation (host-side: no jax import)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardCandidate:
    """One partitioning a segment admits over a mesh.

    ``in_dims`` maps each external input column to the array dim sharded
    over ``axis`` (None = replicated input); ``out_dim`` is the dim device
    outputs stay sharded on (None = replicated outputs — XLA inserts the
    reduce/gather). ``collective`` names the dominant collective the cost
    model prices (``all_gather`` for data-parallel readback, ``all_reduce``
    for feature-sharded partial results)."""

    name: str
    axis: str
    shards: int
    in_dims: Tuple[Tuple[str, Optional[int]], ...]
    out_dim: Optional[int]
    collective: str

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "axis": self.axis, "shards": self.shards,
                "in_dims": dict(self.in_dims), "out_dim": self.out_dim,
                "collective": self.collective}


def candidates(segment, mesh) -> List[ShardCandidate]:
    """Candidate partitionings for one fused Segment over ``mesh``.

    Data parallelism (shard every external input's batch dim over the
    ``data`` axis) is always derived when the axis has >1 devices: fused
    programs are row-independent by the DeviceFn contract, so batch-dim
    sharding is legal by construction. A feature/model-dim candidate over
    the ``tensor`` axis is derived only when EVERY DeviceFn in the segment
    declares a shardable dim for each of its external inputs
    (``DeviceFn.shard_dims``) — GSPMD keeps it correct either way, but an
    undeclared stage gives the cost model nothing to price, so the planner
    does not propose it."""
    out: List[ShardCandidate] = []
    ext = list(segment.external_in_cols)
    shape = dict(getattr(mesh, "shape", {}) or {})
    n_data = int(shape.get(DATA_AXIS, 1))
    if n_data > 1 and ext:
        out.append(ShardCandidate(
            name=SPEC_DATA, axis=DATA_AXIS, shards=n_data,
            in_dims=tuple((c, 0) for c in ext), out_dim=0,
            collective="all_gather"))
    if n_data > 1 and ext and any(
            getattr(dfn, "sparse_fn", None) is not None
            and getattr(dfn, "sparse_cols", ()) for dfn in segment.dfns):
        # row-split CSR over the data axis: each shard takes a contiguous
        # row range of the CSR triple (rebased indptr + its nnz slice) —
        # per-shard nnz is RAGGED, so the readback gather pads to the
        # ragged max and the cost model prices it from the nnz term
        # (``nnz_bytes``), not the dense N·F bytes
        out.append(ShardCandidate(
            name=SPEC_CSR_ROW, axis=DATA_AXIS, shards=n_data,
            in_dims=tuple((c, 0) for c in ext), out_dim=0,
            collective="all_gather"))
    n_tensor = int(shape.get(TENSOR_AXIS, 1))
    if n_tensor > 1 and ext:
        dims: Dict[str, int] = {}
        ok = True
        written: set = set()
        for dfn in segment.dfns:
            decl = getattr(dfn, "shard_dims", None) or {}
            for c in dfn.in_cols:
                if c in written:
                    continue  # internal input: sharding propagates to it
                if c not in decl:
                    ok = False
                    break
                dims[c] = int(decl[c])
            if not ok:
                break
            written |= set(dfn.out_cols)
        if ok and set(dims) >= set(ext):
            out.append(ShardCandidate(
                name=SPEC_FEATURE, axis=TENSOR_AXIS, shards=n_tensor,
                in_dims=tuple((c, dims[c]) for c in ext), out_dim=None,
                collective="all_reduce"))
    return out


def candidate_for(segment, mesh, name: str) -> Optional[ShardCandidate]:
    for cand in candidates(segment, mesh):
        if cand.name == str(name):
            return cand
    return None


def tuner_candidates(segment, mesh, model=None, batch: Optional[int] = None
                     ) -> List[Dict[str, Any]]:
    """Candidate descriptions in the shape ``SegmentCostModel.
    choose_sharding`` prices: ``{name, shards, op, collective_bytes}``.
    ``collective_bytes`` comes from the harvested XLA memory analysis
    (output bytes for the data candidate's readback gather / the feature
    candidate's partial-result reduce); 0 when unharvested — the collective
    term then prices as free and only the flops/shards division differs."""
    out: List[Dict[str, Any]] = []
    label = getattr(segment, "label", str(segment))
    for cand in candidates(segment, mesh):
        nbytes = 0.0
        if model is not None:
            fn = getattr(model, "segment_bytes", None)
            if callable(fn):
                try:
                    nbytes = float(fn(label, "output_bytes") or 0.0)
                except Exception:  # noqa: BLE001 — estimate only
                    nbytes = 0.0
        if cand.name == SPEC_CSR_ROW and model is not None and batch:
            # the csr_row gather moves the RAGGED per-shard nnz payload,
            # not dense rows: price it from the fitted nnz term when the
            # model has one (falls back to the dense output estimate)
            fn = getattr(model, "nnz_bytes", None)
            if callable(fn):
                try:
                    nb = fn(label, int(batch))
                    if nb is not None:
                        nbytes = float(nb)
                except Exception:  # noqa: BLE001 — estimate only
                    pass
        out.append({"name": cand.name, "shards": cand.shards,
                    "op": cand.collective, "collective_bytes": nbytes})
    return out


def split_csr_rows(indptr, indices, values, shards: int
                   ) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Row-split one CSR column into ``shards`` contiguous row ranges —
    the host side of the ``csr_row`` partition spec. Each shard gets a
    REBASED indptr (``ip[0] == 0``) plus exactly its rows' (indices,
    values) slice, so per-shard nnz is ragged. Concatenating the shards'
    predictions in order is bitwise the unsplit prediction: row splitting
    never reorders or duplicates entries (tests/test_sparse_e2e.py)."""
    indptr = np.asarray(indptr, dtype=np.int64)
    indices = np.asarray(indices)
    values = np.asarray(values)
    n = len(indptr) - 1
    shards = max(1, int(shards))
    bounds = [round(i * n / shards) for i in range(shards + 1)]
    out: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
    for lo, hi in zip(bounds, bounds[1:]):
        base = int(indptr[lo])
        end = int(indptr[hi])
        ip = (indptr[lo:hi + 1] - base).astype(np.int32)
        out.append((ip, np.asarray(indices[base:end], dtype=np.int32),
                    np.asarray(values[base:end], dtype=np.float32)))
    return out


def ragged_allgather_bytes(nnz_per_shard: Sequence[int],
                           rows_per_shard: Optional[Sequence[int]] = None
                           ) -> float:
    """All-gather payload for a ragged row-split CSR batch. all_gather is
    rectangular, so every shard's (indices, values) pair pads to the
    ragged max nnz before the gather — the term the cost model fits
    against measured collective seconds, and why a skewed nnz
    distribution erodes the csr_row spec's win even when total nnz is
    small."""
    nnz = [int(x) for x in nnz_per_shard]
    if not nnz:
        return 0.0
    pad = max(max(nnz), 1)
    bytes_iv = len(nnz) * pad * 8.0  # i32 indices + f32 values per slot
    rows = sum(int(r) for r in (rows_per_shard or []))
    return bytes_iv + (rows + len(nnz)) * 4.0  # + rebased indptr slices


# ---------------------------------------------------------------------------
# Runtime sharding handle (executor-facing)
# ---------------------------------------------------------------------------


def donation_supported(mesh) -> bool:
    """Whether pjit input donation buys anything on this mesh's platform.
    CPU backends ignore donation with a per-compile warning — noise, not
    signal — so donation is gated to non-CPU platforms unless
    ``MMLSPARK_SHARD_DONATE=1`` forces it (the bench/CI knob that keeps
    the donate path exercised on forced-host-device meshes)."""
    if os.environ.get("MMLSPARK_SHARD_DONATE", "") == "1":
        return True
    try:
        dev = next(iter(np.asarray(mesh.devices).flat))
        return str(getattr(dev, "platform", "cpu")) != "cpu"
    except Exception:  # noqa: BLE001 — unknown platform: don't donate
        return False


class SegmentSharding:
    """Resolved sharding state for one SegmentExecutor: the NamedShardings,
    pjit kwargs, and sharded staging for one (segment, candidate, mesh).

    Every jax.sharding object is built lazily through the parallel/mesh.py
    helpers (``data_sharding`` / ``replicated_sharding`` — the jax 0.4.37
    compat surface J001 allows). ``device_put`` is the chip-wedge chaos
    seam: ``mesh.chip_wedge`` (core/faults.py) fires per staged batch on
    the SHARDED path only, so injected wedges never perturb the unsharded
    bitwise-parity contract."""

    def __init__(self, segment, mesh, candidate: ShardCandidate):
        self.segment = segment
        self.mesh = mesh
        self.candidate = candidate
        self._in_shardings: Optional[Dict[str, Any]] = None

    @property
    def spec_name(self) -> str:
        return self.candidate.name

    @property
    def shards(self) -> int:
        return int(self.candidate.shards)

    @property
    def axis(self) -> str:
        return self.candidate.axis

    def cache_key(self) -> Tuple:
        """CompileCache key component: a sharded executable must never be
        confused with the single-device one for the same batch shape."""
        return ("spec", self.candidate.name, self.candidate.axis,
                self.shards)

    def shape_prefix(self) -> str:
        """Cost-record shape-key prefix. Deliberately unparseable by
        ``bucket_of_shape`` (like the mega prefix): a sharded executable's
        per-chip flops must not fold into the single-device analytic
        table."""
        return f"spec={self.candidate.name}{self.shards};"

    def _sharding_of(self, dim: Optional[int]):
        if dim is None:
            return replicated_sharding(self.mesh)
        if dim == 0:
            return data_sharding(self.mesh, self.candidate.axis)
        from jax.sharding import NamedSharding, PartitionSpec

        spec = [None] * dim + [self.candidate.axis]
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def input_shardings(self) -> Dict[str, Any]:
        if self._in_shardings is None:
            self._in_shardings = {c: self._sharding_of(dim)
                                  for c, dim in self.candidate.in_dims}
        return dict(self._in_shardings)

    def output_sharding(self):
        return self._sharding_of(self.candidate.out_dim)

    def jit_kwargs(self, mega_k: int = 1) -> Dict[str, Any]:
        """pjit kwargs for the fused program ``fn(params_tuple, cols)``:
        replicated params (pytree-prefix sharding), per-column input
        shardings, the candidate's output sharding, and ``donate_argnums``
        on the ring-staged input dict (argnum 1) — params are NEVER donated
        (they serve every batch). ``mega_k`` > 1 shapes the kwargs for the
        K-tuple-of-dicts mega signature."""
        ins = self.input_shardings()
        cols = tuple(dict(ins) for _ in range(mega_k)) if mega_k > 1 \
            else ins
        kwargs: Dict[str, Any] = {
            "in_shardings": (replicated_sharding(self.mesh), cols),
            "out_shardings": self.output_sharding(),
        }
        if donation_supported(self.mesh):
            kwargs["donate_argnums"] = (1,)
        return kwargs

    def put_params(self, params):
        import jax

        return jax.device_put(params, replicated_sharding(self.mesh))

    def device_put(self, arrays: Dict[str, np.ndarray]) -> Dict[str, Any]:
        """Stage one host batch sharded over the mesh — each column lands
        pre-split across the candidate axis (the slot/deposit staging path
        feeds this the same pre-padded buffers as the single-device put).
        Fires the ``mesh.chip_wedge`` injection point first: an armed delay
        wedges this dispatch (the watchdog's mesh-level prey), an armed
        raise simulates a chip dropping out mid-stage."""
        import jax

        faults.fire(faults.MESH_CHIP_WEDGE)
        ins = self.input_shardings()
        return {c: jax.device_put(v, ins.get(c))
                for c, v in arrays.items()}

    def describe(self) -> Dict[str, Any]:
        return {"spec": self.candidate.name, "axis": self.candidate.axis,
                "shards": self.shards,
                "collective": self.candidate.collective,
                "donate": donation_supported(self.mesh)}


def sharding_for(segment, mesh, name: Optional[str]
                 ) -> Optional[SegmentSharding]:
    """Resolve a tuned sharding knob value into a SegmentSharding, or None
    when it must stay unsharded: no mesh, an unknown/unsupported candidate,
    or a 1-shard axis (a 1-device mesh IS the unsharded path — the
    bitwise-identity contract)."""
    if mesh is None or not name:
        return None
    cand = candidate_for(segment, mesh, name)
    if cand is None or cand.shards <= 1:
        return None
    return SegmentSharding(segment, mesh, cand)


# ---------------------------------------------------------------------------
# Collective probes (the α·bytes calibration source)
# ---------------------------------------------------------------------------


def measure_collectives(mesh, sizes: Sequence[int] = (1 << 14, 1 << 18),
                        repeats: int = 3, model=None,
                        axis: Optional[str] = None) -> List[Dict[str, Any]]:
    """Time real all-reduce / all-gather collectives over the mesh's data
    axis at each payload size (bytes), optionally feeding the cost model's
    ``observe_collective``. Returns the probe records. The probes run via
    ``shard_map_compat`` (parallel/mesh.py) so the measured path is the
    same jax-version-gated machinery the sharded executables use; compile
    time is excluded (one warmup call per (op, size))."""
    import jax
    from jax.sharding import PartitionSpec

    shape = dict(getattr(mesh, "shape", {}) or {})
    if axis is None:
        axis = DATA_AXIS if int(shape.get(DATA_AXIS, 1)) > 1 else \
            max(shape, key=lambda a: shape[a])
    n = int(shape.get(axis, 1))
    if n <= 1:
        return []
    records: List[Dict[str, Any]] = []

    def reduce_fn(a):
        return jax.lax.psum(a, axis)

    def gather_fn(a):
        return jax.lax.all_gather(a, axis, tiled=True)

    for op, body in (("all_reduce", reduce_fn), ("all_gather", gather_fn)):
        for size in sizes:
            elems = max(n, (int(size) // 4 // n) * n)
            x = np.zeros(elems, dtype=np.float32)
            # check_vma off: the all_gather output IS replicated over the
            # axis, but shard_map cannot statically infer that
            fn = shard_map_compat(body, mesh=mesh,
                                  in_specs=PartitionSpec(axis),
                                  out_specs=PartitionSpec(),
                                  check_vma=False)
            jitted = jax.jit(fn)
            jax.block_until_ready(jitted(x))  # compile outside the timing
            t0 = time.perf_counter()
            for _ in range(max(1, int(repeats))):
                jax.block_until_ready(jitted(x))
            seconds = (time.perf_counter() - t0) / max(1, int(repeats))
            rec = {"op": op, "axis": axis, "shards": n,
                   "bytes": elems * 4, "seconds": seconds}
            records.append(rec)
            if model is not None:
                feed = getattr(model, "observe_collective", None)
                if callable(feed):
                    feed(op, elems * 4, seconds)
    return records


# ---------------------------------------------------------------------------
# Mesh-aware supervision: shard groups + submesh re-planning
# ---------------------------------------------------------------------------


def shard_groups(mesh) -> List[List[int]]:
    """Flat-device-index groups that fail TOGETHER: all devices sharing one
    data-axis coordinate (the model-parallel slice a chip computes with —
    when one chip wedges, every partial result in its slice is lost, so
    the whole slice quarantines, not one replica). For a pure data-parallel
    mesh each group is a single device."""
    devs = np.asarray(mesh.devices)
    arr = np.arange(devs.size).reshape(devs.shape)
    axes = list(mesh.axis_names)
    if DATA_AXIS in axes:
        arr = np.moveaxis(arr, axes.index(DATA_AXIS), 0)
    n = arr.shape[0]
    return [[int(i) for i in row] for row in arr.reshape(n, -1)]


def group_of(mesh, device_index: int) -> List[int]:
    """The shard group (flat device indices) containing ``device_index``."""
    idx = int(device_index)
    for grp in shard_groups(mesh):
        if idx in grp:
            return grp
    raise ValueError(f"device index {device_index} not in mesh")


def submesh_excluding(mesh, exclude_devices: Sequence[Any]):
    """A fresh data-parallel mesh over the surviving devices (None when
    none survive). The survivors re-plan as pure data parallelism — the
    safe shape any device count supports; the tuner re-derives richer
    specs once the replacement capacity arrives."""
    dead = set(id(d) for d in exclude_devices)
    survivors = [d for d in np.asarray(mesh.devices).flat
                 if id(d) not in dead]
    if not survivors:
        return None
    return make_mesh(MeshSpec(data=len(survivors)), device_list=survivors)


def mesh_topology(mesh) -> str:
    """Canonical topology string (axis names + sizes + device kind) — the
    persistent compile cache folds this into its environment fingerprint so
    a sharded ``.mmlc`` executable never warm-loads onto a different mesh
    shape (serving/fleet/cache.py)."""
    if mesh is None:
        return "none"
    try:
        shape = dict(mesh.shape)
        axes = ",".join(f"{a}={int(shape[a])}" for a in mesh.axis_names)
        dev = next(iter(np.asarray(mesh.devices).flat))
        kind = getattr(dev, "device_kind", None) or \
            getattr(dev, "platform", "unknown")
        return f"{axes};kind={kind}"
    except Exception:  # noqa: BLE001 — a weird mesh still fingerprints
        return "unknown"


class MeshSupervision:
    """Glue from replica-level supervision to mesh-level repair: owns the
    mesh a FusedPipelineModel shards over, registers the shard groups with
    a ReplicaSupervisor (one supervised index per mesh device), and on a
    wedge quarantines the group + re-plans the model onto the surviving
    submesh (pure data parallelism over the survivors).

    ``on_wedge(device_index)`` is idempotent per group and returns the new
    mesh (None when no devices survive — the model then runs unsharded,
    which is always correct)."""

    def __init__(self, fused, mesh, supervisor=None):
        self.fused = fused
        self.mesh0 = mesh
        self.mesh = mesh
        self.supervisor = supervisor
        self._failed: List[Any] = []
        self.replans = 0
        if supervisor is not None:
            setter = getattr(supervisor, "set_shard_groups", None)
            if callable(setter):
                setter(shard_groups(mesh))
        if fused is not None and hasattr(fused, "set_mesh"):
            fused.set_mesh(mesh)

    def failed_devices(self) -> List[Any]:
        return list(self._failed)

    def on_wedge(self, device_index: int):
        """A chip wedged: quarantine its whole shard group and re-plan the
        fused model over the surviving submesh."""
        group = group_of(self.mesh0, device_index)
        devs = np.asarray(self.mesh0.devices).flat
        fresh = [devs[i] for i in group
                 if not any(devs[i] is f for f in self._failed)]
        if not fresh:
            return self.mesh  # whole group already quarantined: no-op
        self._failed.extend(fresh)
        if self.supervisor is not None:
            wedge = getattr(self.supervisor, "note_wedged", None)
            if callable(wedge):
                wedge(int(device_index))
        sub = submesh_excluding(self.mesh0, self._failed)
        self.mesh = sub
        self.replans += 1
        if self.fused is not None and hasattr(self.fused, "set_mesh"):
            self.fused.set_mesh(sub)
        return sub

    def describe(self) -> Dict[str, Any]:
        return {"topology": mesh_topology(self.mesh),
                "original": mesh_topology(self.mesh0),
                "failed_devices": len(self._failed),
                "replans": self.replans}
