"""Pipeline-parallel mesh execution: fused segments resident on disjoint
``pipe``-axis sub-meshes with device-to-device micro-batch streaming.

The mesh has declared a ``pipe`` axis since PR 14 (parallel/mesh.py) that
no execution path used: shardplan shards WITHIN a segment and
mega-dispatch amortizes dispatch, but a deep fused chain (decode ->
featurize -> DNN -> GBDT) still ran its segments serially on the whole
mesh, with every inter-segment tensor bouncing through the host ring.
This module is the missing execution shape:

  - ``build_pipe_plan(nodes, mesh, depth)`` finds the longest run of
    consecutive fused segments whose device outputs the next segment can
    consume DIRECTLY (``chainable``: the handoff columns' final writers
    have no host ``finalize``, the consumer has no host ``prepare``),
    groups the run into <= ``min(depth, pipe)`` contiguous stages
    balanced by ``SegmentCostModel.predict_ms`` (equal-count while
    uncalibrated), and assigns each stage a disjoint sub-mesh split along
    the pipe axis (non-pipe axes preserved, so ``data``/``feature``
    partition specs still compose INSIDE a stage).
  - ``PipeStageSharding`` is the executor-facing placement handle: by
    default a stage runs REPLICATED over its sub-mesh — GSPMD with fully
    replicated in/out shardings compiles the exact single-device program
    onto the stage's devices, so pipelined replies stay BITWISE-identical
    to serial execution. A tuned per-segment spec (``sharding=`` knob)
    resolves against the SUB-mesh and composes as the ``inner`` sharding
    (that path inherits the sharded contract: allclose, not bitwise —
    tests/test_sharding.py).
  - ``PipeRunner`` streams stage-0's padded micro-batches through the
    stage chain with a bounded in-flight window (default ``depth + 1``):
    each micro-batch is dispatched through EVERY stage before the oldest
    in-flight chain is drained, so all stages stay busy after the
    ``S - 1``-tick fill. Inter-stage tensors move device-to-device with a
    resharding ``jax.device_put`` between the stage shardings — never a
    host readback — and each measured handoff feeds the cost model's
    ``pipe_handoff`` collective fit (the transfer term
    ``predict_pipelined_ms`` prices).
  - A stage whose sub-mesh fails mid-stream (the ``pipe.stage_wedge``
    chaos seam, or a real dispatch/handoff failure) raises
    :class:`StageWedged`; the model quarantines the stage's devices
    (``PipeSupervision`` -> ``ReplicaSupervisor.note_stage_wedged``),
    re-plans at depth N-1 on the survivors via ``degrade_after_wedge``,
    and re-runs the in-flight DataFrame — results are bitwise-identical
    either way, so no request is dropped.

Per-partition contracts the streaming path cannot hold (host-prep rows,
dtype-gate rejections, empty partitions) degrade that partition to the
plain serial executor chain — slower, never wrong — mirroring the fused
host fallback. ``parallel/pipeline_parallel.py``'s ``pipeline_apply``
scan stays the shape-uniform TRAINING idiom; inference segments have
per-stage shapes and executables, so this is its per-stage-dispatch
counterpart with in-flight handoff. docs/pipeline_parallel.md.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import faults
from .mesh import PIPE_AXIS, MeshSpec, make_mesh, replicated_sharding

__all__ = [
    "PIPE_HANDOFF_OP", "StageWedged", "chainable", "chainable_runs",
    "split_segments", "pipe_submeshes", "balance_stages",
    "build_pipe_plan", "PipeStage",
    "PipePlan", "PipeStageSharding", "stage_sharding_for", "PipeRunner",
    "degrade_after_wedge", "PipeSupervision",
]

#: collective-fit key for the measured inter-stage d2d transfer
#: (costmodel.observe_collective / collective_ms)
PIPE_HANDOFF_OP = "pipe_handoff"


class StageWedged(RuntimeError):
    """A pipeline stage's whole sub-mesh failed mid-stream. Unlike a
    single-partition contract violation (which degrades to the serial
    chain), this is a PLACEMENT failure: the model must quarantine the
    stage's devices and re-plan at depth N-1 before re-running."""

    def __init__(self, stage: int, reason: str = ""):
        super().__init__(reason or f"pipeline stage {stage} wedged")
        self.stage = int(stage)


# ---------------------------------------------------------------------------
# plan derivation
# ---------------------------------------------------------------------------


def chainable(prev, nxt) -> bool:
    """Whether ``nxt``'s fused program can consume ``prev``'s DEVICE
    outputs directly — the d2d handoff contract:

      - every external input of ``nxt`` is a device readback of ``prev``
        (not one of its host-demoted columns), so no value must
        round-trip through the host;
      - the FINAL writer of each handoff column has no host ``finalize``
        (the default finalize ships raw arrays, so the device value IS
        the column value bit-for-bit);
      - no stage of ``nxt`` has a host ``prepare`` hook (prep must see
        host rows, which a device-resident handoff never materializes).
    """
    try:
        readback = {k for k, _ in prev.readback_plan(())}
    except Exception:  # noqa: BLE001 — unplannable segment: not chainable
        return False
    avail = readback - set(prev.host_cols)
    ext = list(nxt.external_in_cols)
    if not ext or not set(ext) <= avail:
        return False
    final_writer: Dict[str, Any] = {}
    for dfn in prev.dfns:
        for c in dfn.out_cols:
            final_writer[c] = dfn
    for c in ext:
        writer = final_writer.get(c)
        if writer is None or writer.finalize is not None:
            return False
    return all(dfn.prepare is None for dfn in nxt.dfns)


def chainable_runs(nodes: Sequence[Any]
                   ) -> List[List[Tuple[int, Any]]]:
    """Maximal runs of >= 2 CONSECUTIVE plan nodes that are all fused
    Segments with each adjacent pair chainable — the candidate pipelines
    of a fused plan, as (node index, segment) lists. Shared by
    ``build_pipe_plan`` and the tuner's depth proposal."""
    runs: List[List[Tuple[int, Any]]] = []
    cur: List[Tuple[int, Any]] = []
    for j, node in enumerate(nodes):
        if hasattr(node, "dfns"):
            if cur and cur[-1][0] == j - 1 and chainable(cur[-1][1], node):
                cur.append((j, node))
                continue
            if len(cur) >= 2:
                runs.append(cur)
            cur = [(j, node)]
        else:
            if len(cur) >= 2:
                runs.append(cur)
            cur = []
    if len(cur) >= 2:
        runs.append(cur)
    return runs


def split_segments(nodes: Sequence[Any]) -> List[Any]:
    """The PIPELINE VIEW of a fused plan: every fused Segment is re-cut
    at each clean d2d boundary — the next DeviceFn can head its own
    program (no host ``prepare``) and the handoff columns are
    finalize-free device readbacks of what came before — into maximal
    chainable sub-segments. A single-device plan fuses a whole chain
    into ONE segment because any break there costs a host round-trip; a
    pipeline wants the OPPOSITE cut, so each stage can live on its own
    pipe-axis sub-mesh with tensors moving device-to-device. Serial
    semantics are unchanged: each sub-segment runs the same DeviceFns in
    the same order, and the repo's bitwise contract already holds across
    segment boundaries (fused == unfused per-stage chain). Nodes that
    cannot split pass through unchanged: host stages, single-stage
    segments, and stitched segments (their transpiled finalize shims pin
    host-only columns mid-segment)."""
    out: List[Any] = []
    for node in nodes:
        dfns = getattr(node, "dfns", None)
        if (not dfns or len(dfns) < 2
                or getattr(node, "host_cols", None)):
            out.append(node)
            continue
        out.extend(_split_one(node))
    return out


def _split_one(seg) -> List[Any]:
    """Cut one fused segment at every DeviceFn that can head its own
    program, then re-merge any adjacent pair the ``chainable`` d2d
    contract rejects (a cross-boundary read of an earlier group's
    column, or a boundary writer with a host finalize)."""
    groups: List[List[int]] = [[0]]
    for i in range(1, len(seg.dfns)):
        if seg.dfns[i].prepare is None:
            groups.append([i])
        else:
            groups[-1].append(i)
    if len(groups) == 1:
        return [seg]

    def build(idxs: List[int]):
        sub = type(seg)()
        for i in idxs:
            sub.add(seg.stages[i], seg.dfns[i])
        return sub

    merged = [build(groups[0])]
    gidx = [list(groups[0])]
    for g in groups[1:]:
        sub = build(g)
        if chainable(merged[-1], sub):
            merged.append(sub)
            gidx.append(list(g))
        else:
            gidx[-1].extend(g)
            merged[-1] = build(gidx[-1])
    if len(merged) == 1:
        return [seg]
    return merged


def pipe_submeshes(mesh, depth: int) -> Optional[List[Any]]:
    """Split ``mesh`` into ``depth`` disjoint sub-meshes along the pipe
    axis, preserving every non-pipe axis size — stage i owns pipe
    coordinate group i, and ``data``/``feature`` specs still resolve
    inside each stage. None when the mesh's pipe axis cannot cover
    ``depth`` stages."""
    depth = int(depth)
    shape = dict(getattr(mesh, "shape", {}) or {})
    p = int(shape.get(PIPE_AXIS, 1))
    axes = list(getattr(mesh, "axis_names", ()) or ())
    if depth < 2 or p < depth or PIPE_AXIS not in axes:
        return None
    arr = np.asarray(mesh.devices)
    pipe_idx = axes.index(PIPE_AXIS)
    sizes = {a: int(shape.get(a, 1))
             for a in ("data", "fsdp", "tensor", "seq", "expert")}
    out = []
    for group in np.array_split(np.arange(p), depth):
        sub = np.take(arr, group, axis=pipe_idx)
        # sub keeps the original axis order, so its flat device list
        # reshapes back to exactly this block inside make_mesh
        out.append(make_mesh(MeshSpec(pipe=len(group), **sizes),
                             device_list=list(sub.flat)))
    return out


def balance_stages(costs: Sequence[Optional[float]], depth: int
                   ) -> List[int]:
    """Contiguous stage sizes for a segment run: with a full
    ``predict_ms`` cost vector, minimize the max stage sum (the pipeline
    clock is its slowest stage); with ANY cost unknown, the equal-count
    split — the count-balanced default an uncalibrated model must not
    deviate from."""
    n = len(costs)
    depth = max(1, min(int(depth), n))
    if any(c is None for c in costs):
        return [len(g) for g in np.array_split(np.arange(n), depth)]
    import itertools
    best: Optional[Tuple[int, ...]] = None
    best_max = float("inf")
    for cuts in itertools.combinations(range(1, n), depth - 1):
        bounds = (0,) + cuts + (n,)
        clock = max(sum(float(c) for c in costs[a:b])
                    for a, b in zip(bounds, bounds[1:]))
        if clock < best_max - 1e-12:
            best, best_max = bounds, clock
    if best is None:  # unreachable: depth<=n guarantees one composition
        raise RuntimeError("balance_stages found no contiguous split")
    return [b - a for a, b in zip(best, best[1:])]


@dataclasses.dataclass
class PipeStage:
    """One pipeline stage: a contiguous group of fused segments resident
    on one pipe-axis sub-mesh."""

    index: int
    seg_nodes: Tuple[int, ...]  # plan-node indices of the member segments
    labels: Tuple[str, ...]
    mesh: Any
    predicted_ms: Optional[float] = None

    def device_ids(self) -> List[int]:
        return [int(getattr(d, "id", i)) for i, d in
                enumerate(np.asarray(self.mesh.devices).flat)]

    def describe(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"index": int(self.index),
                               "segments": list(self.labels),
                               "devices": self.device_ids()}
        if self.predicted_ms is not None:
            out["predicted_ms"] = round(float(self.predicted_ms), 4)
        return out


class PipePlan:
    """Placement of one consecutive run of chainable fused segments onto
    pipe-axis sub-mesh stages. ``nodes`` is the PIPELINE VIEW of the
    plan (``split_segments`` — fused segments re-cut at d2d boundaries);
    ``first``/``last`` bound the run inside THAT list (half-open);
    ``stage_of`` maps each member node index to its stage."""

    def __init__(self, stages: Sequence[PipeStage], first: int, last: int,
                 nodes: Optional[Sequence[Any]] = None):
        self.stages = list(stages)
        self.first = int(first)
        self.last = int(last)
        self.nodes = list(nodes) if nodes is not None else None
        self.depth = len(self.stages)
        self.stage_of: Dict[int, int] = {
            n: st.index for st in self.stages for n in st.seg_nodes}

    def describe(self) -> Dict[str, Any]:
        return {"depth": self.depth,
                "stages": [st.describe() for st in self.stages]}


def build_pipe_plan(nodes: Sequence[Any], mesh, depth: int,
                    model=None, batch: Optional[int] = None
                    ) -> Optional["PipePlan"]:
    """Derive a PipePlan from a fused plan: re-cut it into the pipeline
    view (``split_segments``), find the longest (first on tie) run of
    >= 2 consecutive chainable Segment nodes, group it into
    ``min(depth, pipe, run length)`` contiguous stages balanced by
    ``predict_ms`` (equal-count while uncalibrated), and build each
    stage's sub-mesh. The returned plan's ``nodes`` IS that view — the
    executor must run it, not the original plan. None = stay serial: no
    pipe axis to split, no eligible run, or depth < 2 after clamping."""
    if mesh is None:
        return None
    shape = dict(getattr(mesh, "shape", {}) or {})
    p = int(shape.get(PIPE_AXIS, 1))
    if p < 2 or int(depth) < 2:
        return None
    nodes = split_segments(nodes)
    runs = chainable_runs(nodes)
    if not runs:
        return None
    run = max(runs, key=len)
    depth_eff = min(int(depth), p, len(run))
    if depth_eff < 2:
        return None
    submeshes = pipe_submeshes(mesh, depth_eff)
    if submeshes is None:
        return None
    b = int(batch) if batch else run[0][1].batch_size()
    costs: List[Optional[float]] = []
    for _, seg in run:
        ms = None
        if model is not None:
            try:
                if model.calibrated(seg.label):
                    ms = model.predict_ms(seg.label, batch=b)
            except Exception:  # noqa: BLE001 — balance falls back to count
                ms = None
        costs.append(ms)
    sizes = balance_stages(costs, depth_eff)
    stages: List[PipeStage] = []
    k = 0
    for si, size in enumerate(sizes):
        chunk = run[k:k + size]
        chunk_costs = costs[k:k + size]
        k += size
        pred = sum(chunk_costs) \
            if all(c is not None for c in chunk_costs) else None
        stages.append(PipeStage(
            index=si, seg_nodes=tuple(j for j, _ in chunk),
            labels=tuple(seg.label for _, seg in chunk),
            mesh=submeshes[si], predicted_ms=pred))
    return PipePlan(stages, first=run[0][0], last=run[-1][0] + 1,
                    nodes=nodes)


# ---------------------------------------------------------------------------
# stage placement handle
# ---------------------------------------------------------------------------


class PipeStageSharding:
    """Executor-facing placement for one segment of a pipeline stage —
    the same interface SegmentSharding exposes (shardplan.py), so
    ``SegmentExecutor`` needs no pipeline-specific branches.

    Default placement is REPLICATED over the stage's sub-mesh: GSPMD with
    fully replicated in/out shardings degenerates to the original
    single-device program on every stage device, so the pipelined answer
    stays bitwise-identical to serial execution while the stage owns its
    devices. A tuned ``inner`` SegmentSharding (resolved against the
    SUB-mesh) composes on top and carries the sharded (allclose)
    contract."""

    def __init__(self, segment, submesh, stage_index: int, depth: int,
                 inner=None):
        self.segment = segment
        self.mesh = submesh
        self.stage_index = int(stage_index)
        self.depth = int(depth)
        self.inner = inner
        self.device_ids = tuple(
            int(getattr(d, "id", i)) for i, d in
            enumerate(np.asarray(submesh.devices).flat))

    @property
    def shards(self) -> int:
        return self.inner.shards if self.inner is not None else 1

    def cache_key(self) -> Tuple:
        """CompileCache key tail: a stage-resident executable targets THIS
        sub-mesh's devices — key it apart from the single-device program,
        from other stages, and from post-replan placements of the same
        stage index (the device ids pin the exact sub-mesh)."""
        tail = ("pipe", self.stage_index, self.depth, self.device_ids)
        if self.inner is not None:
            return self.inner.cache_key() + tail
        return tail

    def shape_prefix(self) -> str:
        """Decorate the shape key (``pipe=s<i>of<d>;``) so the cost
        model's bucket parser skips stage-resident records generically —
        same contract as ``spec=``/``mega``/``variant`` prefixes."""
        pre = self.inner.shape_prefix() if self.inner is not None else ""
        return f"pipe=s{self.stage_index}of{self.depth};" + pre

    def input_sharding(self, col: str):
        """Placement a handoff column must land in before this stage's
        dispatch (the reshard target of the d2d ``jax.device_put``)."""
        if self.inner is not None:
            sh = self.inner.input_shardings().get(col)
            if sh is not None:
                return sh
        return replicated_sharding(self.mesh)

    def jit_kwargs(self, mega_k: int = 1) -> Dict[str, Any]:
        if self.inner is not None:
            kwargs = dict(self.inner.jit_kwargs(mega_k=mega_k))
            # never donate pipelined inputs: a stage's staged input IS the
            # upstream stage's output buffer, which the drain still reads
            # (collected readbacks) — donation would free it mid-flight
            kwargs.pop("donate_argnums", None)
            return kwargs
        rep = replicated_sharding(self.mesh)
        # a single sharding is a pytree prefix: replicate params and every
        # staged column over the stage sub-mesh
        return {"in_shardings": (rep, rep), "out_shardings": rep}

    def put_params(self, params):
        import jax
        if self.inner is not None:
            return self.inner.put_params(params)
        return jax.device_put(params, replicated_sharding(self.mesh))

    def device_put(self, arrays: Dict[str, Any]):
        """Stage one HOST batch onto the stage sub-mesh — stage 0 of the
        stream only; downstream stages receive device arrays through
        :meth:`reshard`."""
        import jax
        if self.inner is not None:
            return self.inner.device_put(arrays)
        rep = replicated_sharding(self.mesh)
        return {c: jax.device_put(v, rep) for c, v in arrays.items()}

    def reshard(self, arrays: Dict[str, Any]) -> Dict[str, Any]:
        """Device-to-device handoff: move the upstream stage's output
        arrays onto THIS stage's sub-mesh with a resharding
        ``jax.device_put`` — never a host readback."""
        import jax
        return {c: jax.device_put(v, self.input_sharding(c))
                for c, v in arrays.items()}

    def describe(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"stage": self.stage_index,
                               "depth": self.depth,
                               "devices": list(self.device_ids)}
        if self.inner is not None:
            out["spec"] = self.inner.describe()
        return out


def stage_sharding_for(segment, stage: PipeStage, depth: int,
                       spec_name: Optional[str] = None
                       ) -> PipeStageSharding:
    """Build the segment's stage placement, composing its tuned partition
    spec (resolved against the stage SUB-mesh) when one is named and
    resolvable — resolution failure degrades to the replicated (bitwise)
    stage placement, never fails the transform."""
    inner = None
    if spec_name:
        try:
            from .shardplan import sharding_for
            inner = sharding_for(segment, stage.mesh, spec_name)
        except Exception:  # noqa: BLE001 — degrade to replicated stage
            inner = None
    return PipeStageSharding(segment, stage.mesh, stage.index, depth,
                             inner=inner)


# ---------------------------------------------------------------------------
# streaming runner
# ---------------------------------------------------------------------------


class PipeRunner:
    """Streams micro-batches through the pipelined segment chain.

    Stage 0's executor preps/buckets/stages each partition exactly as the
    serial path does (same micro-batch boundaries, same padding); every
    downstream segment consumes its predecessor's DEVICE outputs through
    a synthesized execution state — no host prep, no readback. A bounded
    in-flight window (default ``depth + 1`` chains) keeps every stage
    dispatching while older chains drain. Partitions the streaming
    contract cannot hold run the plain serial executor chain instead.
    """

    def __init__(self, pplan: PipePlan, executors: Sequence[Any],
                 stats: Sequence[Any], cost_model=None,
                 window: Optional[int] = None):
        self.pplan = pplan
        self.execs = list(executors)
        self.stats = list(stats)
        self.cost_model = cost_model
        self.window = max(1, int(window)) if window else pplan.depth + 1
        node_order = sorted(pplan.stage_of)
        #: chain position (0..n_segments-1) -> stage index
        self.seg_stage = [pplan.stage_of[j] for j in node_order]
        self.micro_batches = 0
        self.partitions = 0
        self.serial_parts = 0
        self.busy_s = [0.0] * pplan.depth
        self.handoff_bytes = [0.0] * pplan.depth
        self.handoff_s = [0.0] * pplan.depth
        self.wall_s = 0.0

    # -- public entry ------------------------------------------------------

    def run(self, df):
        import jax

        from ..core.device_stage import FusionUnsupported
        from ..core.fusion import _HostFallback

        t0 = time.perf_counter()
        params = [ex._put_params(jax) for ex in self.execs]
        parts_per_seg: List[List[Dict[str, np.ndarray]]] = \
            [[] for _ in self.execs]
        for part in df.partitions:
            self.partitions += 1
            try:
                outs = self._run_partition(dict(part), params)
                for lst, p in zip(parts_per_seg, outs):
                    lst.append(p)
            except StageWedged:
                raise
            except (_HostFallback, FusionUnsupported):
                # per-partition contract violation: the serial executor
                # chain (with its own host-fallback safety) — bitwise the
                # unpipelined answer, the waste is counted
                self._serial_partition(part, df.schema, parts_per_seg)
        out = df
        for ex, parts in zip(self.execs, parts_per_seg):
            out = ex._overlay(out, parts)
        # the chain overlaps on purpose: per-segment walls would double-
        # count, so the stream's wall lives in the pipeline stats section
        self.wall_s += time.perf_counter() - t0
        return out

    # -- per-partition streaming ------------------------------------------

    def _serial_partition(self, part, schema, parts_per_seg) -> None:
        from ..core.dataframe import DataFrame

        self.serial_parts += 1
        sub = DataFrame([dict(part)], schema.copy())
        for j, ex in enumerate(self.execs):
            sub = ex.run(sub, self.stats[j])
            parts_per_seg[j].extend(sub.partitions)

    def _run_partition(self, part: Dict[str, np.ndarray], params
                       ) -> List[Dict[str, np.ndarray]]:
        from ..core.fusion import _HostFallback

        ex0 = self.execs[0]
        state0 = ex0._prep_partition(part, self.stats[0])
        if state0["n_valid"] <= 0:
            raise _HostFallback("no valid rows to stream")
        states = [state0]
        for ex in self.execs[1:]:
            seg = ex.segment
            readback = seg.readback_plan(ex._transpiled)
            # synthesized state: the segment's inputs arrive device-
            # resident from the upstream stage, so there is no host part,
            # no validity scan and no prepare (the chainable() gate
            # guaranteed none is needed); _emit_partition fills part/
            # valid/n at drain time from the upstream emit
            states.append({
                "part": None, "sub": {}, "ctx": {}, "valid": None,
                "n": None, "n_valid": None,
                "ext": list(seg.external_in_cols),
                "staged_cols": list(seg.external_in_cols),
                "readback": readback,
                "keys": [k for k, _ in readback]})
        steps = [ex._make_step(p, st)
                 for ex, p, st in zip(self.execs, params, states)]
        collected: List[Dict[str, List[np.ndarray]]] = \
            [{k: [] for k in st["keys"]} for st in states]
        inflight: deque = deque()
        first_batch = True
        src, filler = ex0._fill_ahead(state0, self.stats[0])
        try:
            for batch in src:
                chain = self._dispatch_chain(batch, steps, states,
                                             check_gates=first_batch)
                first_batch = False
                self.micro_batches += 1
                inflight.append(chain)
                while len(inflight) > self.window:
                    self._resolve(inflight.popleft(), states, collected)
            while inflight:
                self._resolve(inflight.popleft(), states, collected)
        finally:
            if filler is not None:
                filler.close()
        return self._emit_chain(states, collected)

    def _fire_wedge(self, stage: int) -> None:
        try:
            faults.fire(faults.PIPE_STAGE_WEDGE, stage=int(stage))
        except Exception as e:  # noqa: BLE001 — any armed exc wedges
            raise StageWedged(int(stage), str(e))

    def _dispatch_chain(self, batch, steps, states, check_gates=False):
        """Dispatch one micro-batch through every stage: stage 0 stages
        from host, each stage boundary reshards device-to-device, every
        dispatch is async — the chain returns handles, drained later by
        ``_resolve`` so up to ``window`` chains overlap."""
        from ..parallel.ingest import BatchTiming, timed_stage

        ex0 = self.execs[0]
        s0 = self.seg_stage[0]
        self._fire_wedge(s0)
        staged, timing0 = timed_stage(ex0._put, batch)
        td = time.perf_counter()
        try:
            handle = steps[0](staged)
        except StageWedged:
            raise
        except Exception as e:  # noqa: BLE001 — stage dispatch died
            raise StageWedged(s0, f"stage 0 dispatch failed: {e}")
        now = time.perf_counter()
        timing0.dispatch_s = now - td
        self.busy_s[s0] += now - td
        handles = [handle]
        timings = [timing0]
        env: Dict[str, Any] = dict(zip(states[0]["keys"], handle[0]))
        m = handle[1]
        for j in range(1, len(self.execs)):
            xs = {c: env[c] for c in states[j]["ext"]}
            sj, sprev = self.seg_stage[j], self.seg_stage[j - 1]
            timing = BatchTiming(rows=int(m))
            if xs:
                lead = next(iter(xs.values()))
                timing.padded_rows = int(np.shape(lead)[0] or 0)
            if sj != sprev:
                self._fire_wedge(sj)
                t1 = time.perf_counter()
                try:
                    xs = self.execs[j].sharding.reshard(xs)
                except StageWedged:
                    raise
                except Exception as e:  # noqa: BLE001 — handoff died
                    raise StageWedged(sj, f"handoff to stage {sj} "
                                          f"failed: {e}")
                dt = time.perf_counter() - t1
                nbytes = float(sum(int(getattr(v, "nbytes", 0) or 0)
                                   for v in xs.values()))
                self.handoff_s[sj] += dt
                self.handoff_bytes[sj] += nbytes
                timing.h2d_s = dt  # the stage's ingest IS the d2d handoff
                timing.bytes_in = int(nbytes)
                if self.cost_model is not None and nbytes > 0:
                    obs = getattr(self.cost_model, "observe_collective",
                                  None)
                    if callable(obs):
                        try:
                            obs(PIPE_HANDOFF_OP, nbytes, dt)
                        except Exception:  # noqa: BLE001 — obs-only
                            pass
            if check_gates:
                self._check_gates(j, xs)
            t2 = time.perf_counter()
            try:
                hj = steps[j]((xs, m))
            except StageWedged:
                raise
            except Exception as e:  # noqa: BLE001 — stage dispatch died
                raise StageWedged(sj, f"stage {sj} dispatch failed: {e}")
            now = time.perf_counter()
            timing.dispatch_s = now - t2
            self.busy_s[sj] += now - t2
            handles.append(hj)
            timings.append(timing)
            env.update(zip(states[j]["keys"], hj[0]))
        return handles, timings

    def _check_gates(self, j: int, xs: Dict[str, Any]) -> None:
        """First-micro-batch contract check for a downstream segment: the
        same ``accepts`` dtype gates its serial prep would evaluate on
        materialized rows, evaluated on the device arrays' row view
        (batched leading dim stripped). A failing gate degrades the
        partition to the serial chain — bitwise the unpipelined answer,
        which runs the IDENTICAL gate on host rows."""
        from ..core.fusion import _HostFallback

        ex = self.execs[j]
        probes = {c: {"dtype": np.dtype(v.dtype),
                      "ndim": max(0, int(np.ndim(v)) - 1),
                      "sparse": False, "mixed": False}
                  for c, v in xs.items()}
        for dfn, stage in zip(ex.segment.dfns, ex.segment.stages):
            mine = {c: probes[c] for c in dfn.in_cols if c in probes}
            if mine and dfn.accepts is not None and not dfn.accepts(mine):
                raise _HostFallback(
                    f"{type(stage).__name__} dtype gate (pipelined)")

    def _resolve(self, chain, states, collected) -> None:
        """Drain the oldest in-flight chain: block in stage order (each
        residual wait is that stage's un-hidden compute) and collect every
        segment's readbacks."""
        from ..parallel.ingest import _block_ready

        handles, timings = chain
        for j, (st, handle, timing) in enumerate(zip(states, handles,
                                                     timings)):
            sj = self.seg_stage[j]
            t0 = time.perf_counter()
            _block_ready(handle)
            t1 = time.perf_counter()
            timing.compute_s = t1 - t0
            outs = self.execs[j]._fetch(handle)
            t2 = time.perf_counter()
            timing.readback_s = t2 - t1
            self.busy_s[sj] += t2 - t0
            self.stats[j].record(timing)
            for k, y in zip(st["keys"], outs):
                collected[j][k].append(y)

    def _emit_chain(self, states, collected) -> List[Dict[str, np.ndarray]]:
        """Finalize the chain bottom-up exactly as the serial path would:
        each segment's emit runs over its predecessor's emitted partition,
        with validity collapsing after any ``drop_invalid`` segment (the
        rows are GONE from the downstream frame, so downstream emits see a
        fully valid shorter partition)."""
        outs: List[Dict[str, np.ndarray]] = []
        cur_part = states[0]["part"]
        cur_n = states[0]["n"]
        cur_valid = states[0]["valid"]
        n_valid = states[0]["n_valid"]
        for j, ex in enumerate(self.execs):
            st = states[j]
            if j > 0:
                st["part"] = cur_part
                st["n"] = cur_n
                st["valid"] = cur_valid
                st["n_valid"] = n_valid
            out_part = ex._emit_partition(st, collected[j])
            outs.append(out_part)
            if any(d.drop_invalid for d in ex.segment.dfns) \
                    and n_valid < cur_n:
                cur_n = n_valid
                cur_valid = np.ones(n_valid, dtype=bool)
            cur_part = out_part
        return outs

    # -- stats surface -----------------------------------------------------

    def stats_dict(self, requeues: Optional[Dict[int, int]] = None,
                   replans: int = 0) -> Dict[str, Any]:
        """The ``fusion_stats()["pipeline"]`` section (absent entirely
        when no pipe plan ran). Busy/bubble numbers are honest host-side
        CPU measurements of this run — occupancy evidence, not a device
        profile."""
        wall = max(self.wall_s, 1e-9)
        mb = self.micro_batches
        s = self.pplan.depth
        bubble = (s - 1) / (mb + s - 1) if mb > 0 else 0.0
        stages = []
        for st in self.pplan.stages:
            i = st.index
            d = st.describe()
            d["busy_ms"] = round(self.busy_s[i] * 1e3, 3)
            d["busy_ratio"] = round(min(1.0, self.busy_s[i] / wall), 4)
            d["handoff_bytes"] = int(self.handoff_bytes[i])
            d["handoff_ms"] = round(self.handoff_s[i] * 1e3, 3)
            d["requeues"] = int((requeues or {}).get(i, 0))
            stages.append(d)
        return {"depth": s, "window": self.window, "micro_batches": mb,
                "partitions": self.partitions,
                "serial_fallback_partitions": self.serial_parts,
                "bubble_ratio": round(bubble, 4),
                "handoff_bytes": int(sum(self.handoff_bytes)),
                "handoff_ms": round(sum(self.handoff_s) * 1e3, 3),
                "wall_ms": round(wall * 1e3, 3),
                "replans": int(replans),
                "stages": stages}


# ---------------------------------------------------------------------------
# failure handling / supervision
# ---------------------------------------------------------------------------


def degrade_after_wedge(mesh, pplan: PipePlan, stage_index: int
                        ) -> Tuple[Any, int]:
    """(surviving mesh, new depth) after ``stage_index`` wedged: drop the
    stage's devices, rebuild a ``pipe = depth - 1`` mesh over the
    survivors when they divide evenly, else a flat data mesh at depth 1
    (serial execution on the survivors). (None, 1) when nothing
    survives."""
    dead = {id(d) for d in
            np.asarray(pplan.stages[int(stage_index)].mesh.devices).flat}
    survivors = [d for d in np.asarray(mesh.devices).flat
                 if id(d) not in dead]
    if not survivors:
        return None, 1
    new_depth = int(pplan.depth) - 1
    if new_depth >= 2 and len(survivors) % new_depth == 0:
        try:
            return make_mesh(
                MeshSpec(data=len(survivors) // new_depth,
                         pipe=new_depth),
                device_list=survivors), new_depth
        except Exception:  # noqa: BLE001 — fall through to flat mesh
            pass
    return make_mesh(MeshSpec(data=len(survivors)),
                     device_list=survivors), 1


class PipeSupervision:
    """Extends shard-group quarantine (shardplan.MeshSupervision) to
    pipeline stages: registers each stage's flat device-index group with
    the supervisor, and on a wedged stage quarantines its devices
    (``ReplicaSupervisor.note_stage_wedged``), degrades the mesh, and
    re-arms the model at depth N-1 — the model then re-runs the in-flight
    DataFrame on the surviving sub-meshes, bitwise-identical, no request
    dropped."""

    def __init__(self, fused, mesh, supervisor=None):
        self.fused = fused
        self.mesh0 = mesh
        self.mesh = mesh
        self.supervisor = supervisor
        self.replans = 0
        self.depth: Optional[int] = None
        self._registered = False
        if fused is not None:
            fused._pipe_wedge_handler = self.on_stage_wedge
            fused._pipe_supervision = self
            if hasattr(fused, "set_mesh"):
                fused.set_mesh(mesh)

    def register(self, pplan: PipePlan) -> None:
        """Hand the plan's stage device groups (flat indices into the
        ORIGINAL mesh) to the supervisor, mirroring set_shard_groups."""
        self.depth = pplan.depth
        if self.supervisor is None:
            return
        setter = getattr(self.supervisor, "set_pipe_stages", None)
        if not callable(setter):
            return
        devs = list(np.asarray(self.mesh0.devices).flat)
        groups = []
        for st in pplan.stages:
            members = [i for i, d in enumerate(devs)
                       if any(d is sd for sd in
                              np.asarray(st.mesh.devices).flat)]
            groups.append(members)
        setter(groups)
        self._registered = True

    def on_stage_wedge(self, pplan: PipePlan, stage_index: int):
        """The model's wedge callback: quarantine, degrade, re-arm."""
        if not self._registered:
            self.register(pplan)
        if self.supervisor is not None:
            noter = getattr(self.supervisor, "note_stage_wedged", None)
            if callable(noter):
                noter(int(stage_index))
        new_mesh, new_depth = degrade_after_wedge(self.mesh, pplan,
                                                  stage_index)
        self.mesh = new_mesh
        self.depth = new_depth
        self.replans += 1
        if self.fused is not None:
            if hasattr(self.fused, "set_mesh"):
                self.fused.set_mesh(new_mesh)
            if hasattr(self.fused, "set_tuning"):
                self.fused.set_tuning(pipe_depth=new_depth)
        return new_mesh

    def describe(self) -> Dict[str, Any]:
        from .shardplan import mesh_topology
        return {"topology": mesh_topology(self.mesh),
                "original": mesh_topology(self.mesh0),
                "depth": self.depth, "replans": self.replans}
