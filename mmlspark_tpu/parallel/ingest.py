"""Unified device-ingest layer: uint8 wire format + transfer ring + stats.

The framework's data plane. BENCH_r05 showed the flagship featurize path
computing at ~11.5k images/sec/chip per-call but only ~260 images/sec
end-to-end: the DataFrame -> device ingest path, not XLA compute, was the
bottleneck (h2d_gbps = 0.036). Two structural fixes live here:

  - **uint8 on the wire** (``PreprocessSpec``): the host stops doing
    ``astype(float32) * scale`` (+ layout transpose) per image; batches ship
    in their decoded dtype (uint8 pixels = 4x fewer H2D bytes) and the
    cast/scale/transpose runs INSIDE the consumer's jitted forward, where
    XLA fuses it with the first conv's bf16 cast for free.
  - **transfer ring** (``TransferRing``): a configurable number of in-flight
    batches replaces ad-hoc double buffering. H2D runs on a background
    thread (overlapping the previous batch's compute), up to ``depth``
    dispatched steps stay in flight, and results drain in order. Every
    stage is timed per batch into an ``IngestStats`` object, so the
    e2e-vs-per-call gap is a first-class measured quantity.

Consumers: DNNModel (models/dnn_model.py) for the DataFrame eval path,
DeviceEnsemble (gbdt/predict.py) for chunked GBDT scoring, and bench.py's
e2e section. The ring is generic — anything shaped
``host batches -> stage -> dispatch -> readback`` can ride it.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core import faults
from .batching import DevicePrefetcher


# ---------------------------------------------------------------------------
# PreprocessSpec: host preprocessing moved into the compiled forward
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PreprocessSpec:
    """Device-side preprocessing fused into a jitted forward.

    Describes what the host USED to do to each row before batching —
    ``astype(float32) * scale + offset`` and an optional per-row axes
    transpose (NHWC -> NCHW for ONNX imports) — so the wire carries the raw
    decoded dtype and the work runs on device, inside jit. Hashable, so
    compiled-forward caches can key on it.

    ``transpose`` is the PER-ROW axes permutation (e.g. ``(2, 0, 1)`` for
    HWC -> CHW); the batched device op shifts it past the leading batch dim.
    ``dtype``: compute dtype after the cast (float32 unless doing f64
    numerics experiments).
    """

    scale: float = 1.0
    offset: float = 0.0
    transpose: Optional[Tuple[int, ...]] = None
    dtype: str = "float32"

    def __post_init__(self):
        if self.transpose is not None:
            object.__setattr__(self, "transpose",
                               tuple(int(a) for a in self.transpose))

    @property
    def is_identity(self) -> bool:
        return (self.scale == 1.0 and self.offset == 0.0
                and self.transpose is None and self.dtype == "float32")

    def cache_key(self) -> Tuple:
        """Pure-literal tuple form for compile-cache keys. The persistent
        fleet tier (serving/fleet/cache.py) round-trips keys through
        ``repr``/``ast.literal_eval`` — a dataclass repr would survive
        repr but not the (deliberately eval-free) parse, demoting warm-up
        from AOT-by-name to lazy-at-first-request."""
        return ("PreprocessSpec", float(self.scale), float(self.offset),
                self.transpose, self.dtype)

    def _batch_axes(self, ndim: int) -> Tuple[int, ...]:
        perm = self.transpose
        if perm is None or len(perm) != ndim - 1:
            raise ValueError(
                f"transpose {perm} does not match per-row rank {ndim - 1}")
        return (0,) + tuple(a + 1 for a in perm)

    def apply_device(self, x):
        """Batched [B, ...] device op, trace-safe under jit."""
        import jax.numpy as jnp

        dt = getattr(jnp, self.dtype)
        y = x.astype(dt)
        if self.scale != 1.0:
            y = y * dt(self.scale)
        if self.offset != 0.0:
            y = y + dt(self.offset)
        if self.transpose is not None:
            y = jnp.transpose(y, self._batch_axes(y.ndim))
        return y

    def apply_host(self, x: np.ndarray) -> np.ndarray:
        """Numpy reference of ``apply_device`` on a [B, ...] batch — the
        numerical-parity oracle (uint8 -> f32 cast and an f32 multiply are
        exact, so host and device agree bitwise) and the fallback for
        consumers that never reach a device."""
        dt = np.dtype(self.dtype).type
        y = x.astype(dt)
        if self.scale != 1.0:
            y = y * dt(self.scale)
        if self.offset != 0.0:
            y = y + dt(self.offset)
        if self.transpose is not None:
            y = np.transpose(y, self._batch_axes(y.ndim))
        return y

    def apply_host_row(self, img: np.ndarray) -> np.ndarray:
        """Per-row host application (the legacy featurizer prep path)."""
        return self.apply_host(img[None])[0]


# ---------------------------------------------------------------------------
# IngestStats: per-batch ingest decomposition
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BatchTiming:
    """Wall-clock decomposition of one batch through the ring (seconds).

    ``queue_s``  — consumer wait for the prefetched batch (producer-bound
                   time: decode/stack upstream plus H2D not yet hidden).
    ``h2d_s``    — host->device transfer, measured ON the producer thread
                   (device_put + block-until-ready), so it overlaps compute.
    ``dispatch_s`` — host cost of enqueueing the compiled step (async).
    ``compute_s``  — residual wait for the step's outputs at drain time
                   (0 when compute fully hid behind later batches' ingest).
    ``readback_s`` — device->host fetch of the outputs.
    ``bytes_in`` — wire bytes shipped for this batch.
    ``rows``     — valid rows in the batch.
    ``padded_rows`` — the static bucket size the batch was padded to (0 =
                   unpadded/unknown); ``padded_rows - rows`` is pure
                   pad-waste compute, the cost-model term the bucket
                   auto-tuner (core/costmodel.py) minimizes.
    ``mega_k``   — dispatch-amortization group size this batch rode (1 =
                   plain per-batch dispatch). When > 1, ``dispatch_s`` is
                   the per-batch SHARE of one K-step mega dispatch;
                   ``dispatch_s * mega_k`` recovers the per-Python-call
                   fixed cost the cost model's ``choose_mega_k`` needs —
                   without the tag, an active K>1 makes dispatch look
                   cheap, the tuner proposes K=1, and K oscillates.
    """

    queue_s: float = 0.0
    h2d_s: float = 0.0
    dispatch_s: float = 0.0
    compute_s: float = 0.0
    readback_s: float = 0.0
    bytes_in: int = 0
    rows: int = 0
    padded_rows: int = 0
    mega_k: int = 1


class IngestStats:
    """Accumulates ``BatchTiming`` rows plus ring wall time; ``summary()``
    renders the e2e decomposition bench.py and the serving stats endpoint
    surface. Safe to share across sequential ring runs (partitions of one
    transform accumulate into one object)."""

    def __init__(self):
        self.records: List[BatchTiming] = []
        self.wall_s: float = 0.0
        # ring slot occupancy (dispatched-but-undrained steps): configured
        # depth + running mean/max of observed fill, so "is the ring ever
        # actually full?" is a scraped gauge instead of a rerun experiment
        self.ring_depth: int = 0
        self._occ_sum: int = 0
        self._occ_n: int = 0
        self._occ_max: int = 0
        # pad-waste per bucket: {padded size: [batches, real rows]} — the
        # measured term behind mmlspark_batch_pad_ratio{bucket=} and the
        # cost model's bucket chooser (assumed-waste becomes measured-waste)
        self._pad: Dict[int, List[int]] = {}
        # deposit accounting (docs/ingest.md): batches staged zero-alloc
        # into SlotPool slots vs batches that took the accounted copying
        # fallback (mmlspark_ingest_deposits_total / _copies_total)
        self.deposits: int = 0
        self.copies: int = 0
        # rows_to_batch outcome split: spanning zero-copy views vs stacked
        # copies (mmlspark_ingest_zero_copy_batches_total / _copied_...)
        self.zero_copy_batches: int = 0
        self.copied_batches: int = 0
        # per-slot double-buffer decomposition: fill / transfer seconds and
        # the measured fill<->transfer overlap between paired buffers
        self.slot_fill_s: float = 0.0
        self.slot_transfer_s: float = 0.0
        self.slot_overlap_s: float = 0.0
        self.slot_transfers: int = 0
        # sparse-layout accounting (docs/sparse.md): bytes a densify
        # materialized vs the CSR bytes the same rows would have shipped
        # (mmlspark_ingest_densified_bytes_total / _densify_ratio), and the
        # CSR-through counterpart (bytes actually staged as triples vs the
        # dense-equivalent bytes avoided). All zero — and absent from
        # summary() — until sparse data is seen.
        self.densified_bytes: int = 0
        self.densify_nnz_bytes: int = 0
        self.densifies: int = 0
        self.csr_nnz_bytes: int = 0
        self.csr_dense_bytes: int = 0
        self.csr_batches: int = 0

    def record(self, t: BatchTiming) -> None:
        self.records.append(t)
        if t.padded_rows > 0:
            self.note_padding(t.padded_rows, t.rows)

    def note_padding(self, bucket: int, rows: int) -> None:
        """Count one batch padded to ``bucket`` static rows with ``rows``
        real ones (callable directly by batchers outside the ring)."""
        acc = self._pad.setdefault(int(bucket), [0, 0])
        acc[0] += 1
        acc[1] += int(rows)

    def add_wall(self, seconds: float) -> None:
        self.wall_s += seconds

    def note_ring(self, depth: int) -> None:
        self.ring_depth = max(self.ring_depth, int(depth))

    def note_occupancy(self, in_flight: int) -> None:
        n = int(in_flight)
        self._occ_sum += n
        self._occ_n += 1
        self._occ_max = max(self._occ_max, n)

    def note_deposit(self) -> None:
        """One batch staged in place into a pre-allocated slot."""
        self.deposits += 1

    def note_copy(self) -> None:
        """One batch that took the accounted copying fallback (deposit
        ineligible: dtype narrowing, ragged rows, slot contention, or a
        transfer fault) — the ``mmlspark_ingest_copies_total`` counter."""
        self.copies += 1

    def note_batch_copy(self, zero_copy: bool) -> None:
        """rows_to_batch outcome: spanning zero-copy view vs stacked copy."""
        if zero_copy:
            self.zero_copy_batches += 1
        else:
            self.copied_batches += 1

    def note_densify(self, densified_bytes: int, nnz_bytes: int) -> None:
        """One sparse column densified on the host path: the dense bytes it
        materialized vs the CSR bytes the same rows hold — the measured
        waste the layout knob exists to remove."""
        self.densified_bytes += int(densified_bytes)
        self.densify_nnz_bytes += int(nnz_bytes)
        self.densifies += 1

    def note_csr(self, nnz_bytes: int, dense_bytes: int) -> None:
        """One batch staged as a CSR triple: the triple's actual bytes vs
        the dense-equivalent bytes the densify path would have shipped."""
        self.csr_nnz_bytes += int(nnz_bytes)
        self.csr_dense_bytes += int(dense_bytes)
        self.csr_batches += 1

    def note_slot(self, fill_s: float, transfer_s: float,
                  overlap_s: float) -> None:
        """One slot cycle: host fill seconds, H2D transfer seconds, and the
        measured overlap between this transfer and the paired buffer's
        concurrent fill (double-buffering effectiveness, per slot)."""
        self.slot_fill_s += float(fill_s)
        self.slot_transfer_s += float(transfer_s)
        self.slot_overlap_s += float(overlap_s)
        self.slot_transfers += 1

    def merge(self, other: "IngestStats") -> None:
        """Fold another stats object in (segment aggregation)."""
        self.records.extend(other.records)
        self.wall_s += other.wall_s
        self.ring_depth = max(self.ring_depth, other.ring_depth)
        self._occ_sum += other._occ_sum
        self._occ_n += other._occ_n
        self._occ_max = max(self._occ_max, other._occ_max)
        for bucket, (batches, rows) in other._pad.items():
            acc = self._pad.setdefault(bucket, [0, 0])
            acc[0] += batches
            acc[1] += rows
        self.deposits += other.deposits
        self.copies += other.copies
        self.zero_copy_batches += other.zero_copy_batches
        self.copied_batches += other.copied_batches
        self.slot_fill_s += other.slot_fill_s
        self.slot_transfer_s += other.slot_transfer_s
        self.slot_overlap_s += other.slot_overlap_s
        self.slot_transfers += other.slot_transfers
        self.densified_bytes += other.densified_bytes
        self.densify_nnz_bytes += other.densify_nnz_bytes
        self.densifies += other.densifies
        self.csr_nnz_bytes += other.csr_nnz_bytes
        self.csr_dense_bytes += other.csr_dense_bytes
        self.csr_batches += other.csr_batches

    @property
    def num_batches(self) -> int:
        return len(self.records)

    def _pad_summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        padding: Dict[str, Any] = {}
        tot_real = tot_padded = 0
        for bucket in sorted(self._pad):
            batches, real = self._pad[bucket]
            padded = batches * bucket
            tot_real += real
            tot_padded += padded
            padding[str(bucket)] = {
                "batches": batches, "rows": real, "padded": padded,
                # fraction of the bucket's compute spent on pad rows
                "pad_ratio": round(1 - real / padded, 4) if padded
                else None}
        out["padding"] = padding
        if tot_padded:
            out["pad_ratio"] = round(1 - tot_real / tot_padded, 4)
        return out

    def _staging_summary(self) -> Dict[str, Any]:
        """Deposit / zero-copy / slot-overlap section (only populated
        keys, so summaries without staging activity are unchanged)."""
        out: Dict[str, Any] = {}
        if self.deposits or self.copies:
            out["slot_deposits"] = self.deposits
            out["fallback_copies"] = self.copies
        if self.zero_copy_batches or self.copied_batches:
            out["zero_copy_batches"] = self.zero_copy_batches
            out["copied_batches"] = self.copied_batches
        if self.slot_transfers:
            out["slot_fill_s"] = round(self.slot_fill_s, 6)
            out["slot_transfer_s"] = round(self.slot_transfer_s, 6)
            out["slot_overlap_s"] = round(self.slot_overlap_s, 6)
            # fraction of transfer time hidden behind the paired buffer's
            # fill (1.0 = every transfer fully overlapped a fill)
            out["slot_overlap_ratio"] = round(
                self.slot_overlap_s / self.slot_transfer_s, 4) \
                if self.slot_transfer_s > 0 else None
        if self.densifies:
            out["densifies"] = self.densifies
            out["densified_bytes"] = self.densified_bytes
            out["densify_nnz_bytes"] = self.densify_nnz_bytes
            # dense bytes materialized per CSR byte the rows actually hold
            # (the layout knob's headroom; 1.0 = densify was free)
            out["densify_ratio"] = round(
                self.densified_bytes / self.densify_nnz_bytes, 4) \
                if self.densify_nnz_bytes > 0 else None
        if self.csr_batches:
            out["csr_batches"] = self.csr_batches
            out["csr_nnz_bytes"] = self.csr_nnz_bytes
            out["csr_dense_bytes"] = self.csr_dense_bytes
        return out

    def summary(self) -> Dict[str, Any]:
        if not self.records:
            out = {"n_batches": 0}
            if self._pad:
                out.update(self._pad_summary())
            out.update(self._staging_summary())
            return out
        cols = {f: float(sum(getattr(r, f) for r in self.records))
                for f in ("queue_s", "h2d_s", "dispatch_s", "compute_s",
                          "readback_s")}
        total_bytes = int(sum(r.bytes_in for r in self.records))
        rows = int(sum(r.rows for r in self.records))
        serial = sum(cols.values())
        n = len(self.records)
        out: Dict[str, Any] = {
            "n_batches": n,
            "rows": rows,
            "bytes": total_bytes,
            "wall_s": round(self.wall_s, 6),
            # < 1.0 means the ring hid ingest behind compute (and vice
            # versa); 1.0 = fully serial pipeline
            "overlap_ratio": round(self.wall_s / serial, 4) if serial > 0
            else None,
            "h2d_gbps": round(total_bytes / cols["h2d_s"] / 1e9, 4)
            if cols["h2d_s"] > 0 else None,
        }
        if self.ring_depth > 0:
            out["ring_depth"] = self.ring_depth
            if self._occ_n > 0:
                out["ring_occupancy_mean"] = round(
                    self._occ_sum / self._occ_n, 4)
                out["ring_occupancy_max"] = self._occ_max
        if self._pad:
            out.update(self._pad_summary())
        out.update(self._staging_summary())
        for f, v in cols.items():
            out[f] = round(v, 6)
            out[f"{f[:-2]}_ms_per_batch"] = round(v / n * 1e3, 4)
        return out


def _root_exporter(a: np.ndarray):
    """The object that OWNS an array view's memory: walk the ``.base``
    chain to the final ndarray, and through a memoryview to its exporter
    (``decode_frame`` views are frombuffer-over-memoryview-slice; the slice
    keeps the WHOLE exporter alive, which is what makes a spanning strided
    view over sibling slices memory-safe)."""
    b = a
    while isinstance(b, np.ndarray) and b.base is not None:
        b = b.base
    if isinstance(b, memoryview):
        try:
            return b.obj
        except Exception:  # noqa: BLE001 — released/exotic memoryview
            return b
    return b


def _spanning_view(arrs: List[np.ndarray], shape: Tuple[int, ...],
                   ) -> Optional[np.ndarray]:
    """Zero-copy [B, ...] view when the rows sit at a CONSTANT pointer
    stride inside one live buffer; None otherwise.

    Two layouts qualify: adjacent rows (stride == row nbytes — a whole
    batch shipped in one frame column, or journal replay of a concatenated
    region) and rows spanning multiple PIPELINED FRAMES of one connection
    buffer (stride > row nbytes: equal-size frames back-to-back put each
    frame's payload at payload+header intervals). The second layout is
    only taken when every row resolves to the SAME root exporter object —
    rows from unrelated buffers must never be bridged by pointer
    arithmetic, no matter how adjacent they happen to land."""
    nb = arrs[0].nbytes
    if len(arrs) < 2 or not nb \
            or not all(a.flags["C_CONTIGUOUS"] for a in arrs):
        return None
    try:
        ptrs = [a.__array_interface__["data"][0] for a in arrs]
    except (KeyError, TypeError):
        return None
    stride = ptrs[1] - ptrs[0]
    if stride < nb or any(p != ptrs[0] + i * stride
                          for i, p in enumerate(ptrs)):
        return None
    if stride > nb:
        root = _root_exporter(arrs[0])
        if any(_root_exporter(a) is not root for a in arrs[1:]):
            return None
    # one spanning view over the shared buffer; arrs[0] rides along as
    # .base so the underlying memory stays alive
    return np.lib.stride_tricks.as_strided(
        arrs[0], shape=(len(arrs),) + shape,
        strides=(stride,) + arrs[0].strides)


def rows_to_batch(rows, out: Optional[np.ndarray] = None,
                  stats: Optional["IngestStats"] = None) -> np.ndarray:
    """Per-row arrays -> one contiguous [B, ...] batch for H2D staging.

    The binary-wire ingest path: ``decode_frame`` hands each request's
    payload back as a zero-copy VIEW over its body bytes, and this is the
    single host copy that remains — the batch stack that doubles as the
    transfer ring's staging buffer (uint8 on the wire, cast/scale on
    device via PreprocessSpec).

    Fast path: when the rows sit at one constant stride over ONE live
    buffer (a client shipped a whole batch in one frame column, journal
    replay of a concatenated region, or pipelined equal-size frames of one
    connection), the batch is a strided view — zero copies end-to-end.
    Otherwise ``np.stack``. Rows must agree on shape and dtype (ragged
    batches stay on the per-row host path).

    ``out``: slot-fill mode — a pre-allocated [cap, ...] staging slot
    (SlotPool buffer) receiving the rows in place; returns ``out[:B]``.
    ``stats``: optional IngestStats receiving the zero-copy vs copied
    batch counters.

    A fused segment that re-enters the device after a terminal host
    finalize pays this re-batch per boundary crossing; the cross-segment
    stitch (docs/compiler_search.md) removes that call entirely for
    stitched plans — downstream stages ride the segment's device-resident
    columns, so this path only runs where a genuine host boundary
    remains."""
    arrs = [np.asarray(r) for r in rows]
    if not arrs:
        raise ValueError("rows_to_batch needs at least one row")
    shape, dt = arrs[0].shape, arrs[0].dtype
    for a in arrs[1:]:
        if a.shape != shape or a.dtype != dt:
            raise ValueError(
                f"ragged batch: {a.shape}/{a.dtype} vs {shape}/{dt}")
    if out is not None:
        # slot-fill: rows land in the caller's slot — stack + pad collapse
        # into this ONE copy (the H2D staging buffer is the destination)
        if out.dtype != dt or tuple(out.shape[1:]) != shape \
                or len(out) < len(arrs):
            raise ValueError(
                f"slot [{len(out)}]{out.shape[1:]}/{out.dtype} cannot "
                f"receive batch [{len(arrs)}]{shape}/{dt}")
        view = _spanning_view(arrs, shape) if len(arrs) > 1 else None
        if view is not None:
            out[:len(arrs)] = view  # one bulk memcpy
        else:
            for i, a in enumerate(arrs):
                out[i] = a
        if stats is not None:
            stats.note_batch_copy(zero_copy=False)
        return out[:len(arrs)]
    if len(arrs) == 1:
        if arrs[0].flags["C_CONTIGUOUS"]:
            if stats is not None:
                stats.note_batch_copy(zero_copy=True)
            return arrs[0][None]
        if stats is not None:
            stats.note_batch_copy(zero_copy=False)
        return np.ascontiguousarray(arrs[0])[None]
    view = _spanning_view(arrs, shape)
    if view is not None:
        if stats is not None:
            stats.note_batch_copy(zero_copy=True)
        return view
    if stats is not None:
        stats.note_batch_copy(zero_copy=False)
    return np.stack(arrs)


# ---------------------------------------------------------------------------
# SlotPool: pre-allocated, double-buffered H2D staging slots
# ---------------------------------------------------------------------------


class _SlotBucket:
    """Paired pre-allocated buffers for one (column, batch shape, dtype)
    bucket. Two buffers = double buffering: one fills while the sibling
    transfers. ``fills`` holds this bucket's recent completed fill
    intervals — a transfer's overlap is measured against its OWN bucket's
    sibling fills only, never against unrelated leases elsewhere in the
    shared pool. ``tick`` is the pool's LRU clock value at last use."""

    __slots__ = ("bufs", "free", "fills", "tick")

    def __init__(self, shape: Tuple[int, ...], dtype, n: int):
        self.bufs = [np.zeros(shape, dtype=dtype) for _ in range(n)]
        self.free = list(range(n))
        self.fills: deque = deque(maxlen=8)
        self.tick = 0

    @property
    def nbytes(self) -> int:
        return sum(b.nbytes for b in self.bufs)


class SlotLease:
    """One acquired staging slot: a pre-allocated ``[cap, ...]`` buffer per
    deposit column of one batch. Lifecycle: ``fill_begin``/``fill_end``
    around the host fill, then ``transfer_begin``/``transfer_end`` driven
    by ``timed_stage`` around the H2D transfer — ``transfer_end`` records
    the fill/transfer/overlap decomposition into IngestStats and returns
    the buffers to the pool. ``release()`` is the idempotent abandon path
    (a faulted transfer frees the buffers without recording a cycle; the
    slot content is simply overwritten on reuse, never read). A weakref
    finalizer backstops release: a lease dropped on any abort path (queue
    drain, injected fault, watchdog kill) still returns its buffers to the
    shared, never-replenished pool instead of shrinking it forever."""

    __slots__ = ("arrays", "_pool", "_held", "_stats", "_fill", "_tx0",
                 "_done", "_finalizer", "__weakref__")

    def __init__(self, pool: "SlotPool", held: List[Tuple[Tuple, int]],
                 arrays: Dict[str, np.ndarray], stats):
        import weakref

        self.arrays = arrays
        self._pool = pool
        self._held = held
        self._stats = stats
        self._fill = (0.0, 0.0)
        self._tx0: Optional[float] = None
        self._done = False
        self._finalizer = weakref.finalize(self, pool._release, held)

    def fill_begin(self) -> None:
        self._fill = (time.perf_counter(), 0.0)

    def fill_end(self) -> None:
        self._fill = (self._fill[0], time.perf_counter())
        self._pool._note_fill(self._held, self._fill)

    def transfer_begin(self) -> None:
        self._tx0 = time.perf_counter()

    def transfer_end(self) -> None:
        tx1 = time.perf_counter()
        tx0 = self._tx0 if self._tx0 is not None else tx1
        if self._stats is not None:
            fill_s = max(0.0, self._fill[1] - self._fill[0])
            self._stats.note_slot(fill_s, tx1 - tx0,
                                  self._pool._overlap(self._held, tx0, tx1))
        self.release()

    def release(self) -> None:
        if self._done:
            return
        self._done = True
        # the finalizer IS the release (calling it runs pool._release once
        # and detaches, so a later GC never double-frees)
        self._finalizer()


class SlotPool:
    """Pre-allocated, shape-bucket-keyed H2D staging slots with PAIRED
    buffers per bucket (tentpole piece 2 of the single-copy ingress path,
    docs/ingest.md).

    ``acquire(spec)`` hands out a ``SlotLease`` over one buffer per
    requested column, keyed by (column, full batch shape, dtype) — the
    shape-bucket key, so every padded bucket size reuses its own slot
    instead of allocating per batch. Each bucket holds
    ``buffers_per_bucket`` (default 2) buffers: while buffer A is in H2D
    transfer, buffer B fills — the per-slot overlap is MEASURED (lease
    transfer intervals intersected with concurrent fill intervals) and
    reported through ``IngestStats.note_slot``.

    ``acquire`` is all-or-nothing under one condition variable (no partial
    holds, no lock-order deadlocks) and returns None instead of blocking
    past ``acquire_timeout_s`` — callers fall back to the accounted
    copying path (``IngestStats.note_copy``), so slot contention degrades
    to today's behavior instead of stalling the ring.

    Buffer ALLOCATION happens outside the lock (a 256MB ``np.zeros`` must
    not stall every concurrent acquire/release), and total pool memory is
    bounded by ``max_total_bytes``: inserting a new bucket first evicts
    least-recently-used fully-free buckets, and when no room can be made
    the acquire returns None (copy-path fallback) instead of growing
    without limit across the distinct shapes a long-lived server sees."""

    def __init__(self, buffers_per_bucket: int = 2,
                 max_slot_bytes: int = 1 << 28,
                 max_total_bytes: int = 1 << 31,
                 acquire_timeout_s: float = 2.0):
        import threading

        self._nbuf = max(1, int(buffers_per_bucket))
        self._max_bytes = int(max_slot_bytes)
        self._max_total = int(max_total_bytes)
        self._timeout = float(acquire_timeout_s)
        self._cv = threading.Condition()
        self._buckets: Dict[Tuple, _SlotBucket] = {}
        self._tick = 0          # LRU clock (monotonic acquire counter)
        self._evictions = 0

    def _missing_buckets(self, keys: Dict[str, Tuple],
                         spec: Dict[str, Tuple[Tuple[int, ...], Any]]
                         ) -> Optional[List[Tuple]]:
        """Under self._cv: keys not yet backed by a bucket, as (key, shape,
        dtype, nbytes) build specs. None when any slot exceeds the per-slot
        byte cap (caller falls back to the copying path)."""
        missing = []
        for col, key in keys.items():
            if key in self._buckets:
                continue
            shape, dtype = spec[col]
            nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
            if nbytes <= 0 or nbytes > self._max_bytes:
                return None
            missing.append((key, tuple(int(d) for d in shape), dtype,
                            nbytes))
        return missing

    def _make_room(self, need: int, protect: frozenset) -> bool:
        """Under self._cv: evict LRU fully-free buckets until ``need`` more
        bytes fit under ``max_total_bytes``. False when in-use buckets pin
        the pool above the cap (leased buffers are never evicted — a stale
        release into a re-created bucket is guarded, but yanking live
        buffers is not recoverable). ``protect``: keys the CURRENT acquire
        needs — evicting a sibling bucket of the same spec would ping-pong
        build/evict forever."""
        total = sum(b.nbytes for b in self._buckets.values())
        while total + need > self._max_total:
            victim_key, victim = None, None
            for key, b in self._buckets.items():
                if key not in protect and len(b.free) == len(b.bufs) and \
                        (victim is None or b.tick < victim.tick):
                    victim_key, victim = key, b
            if victim is None:
                return False
            del self._buckets[victim_key]
            total -= victim.nbytes
            self._evictions += 1
        return True

    def acquire(self, spec: Dict[str, Tuple[Tuple[int, ...], Any]],
                stats=None,
                timeout: Optional[float] = None) -> Optional[SlotLease]:
        """``spec``: {column: (full batch shape INCLUDING the leading
        padded cap, dtype)}. Returns a SlotLease, or None on timeout /
        uncacheable shape / a full pool (caller copies and accounts it)."""
        if not spec:
            return None
        deadline = time.perf_counter() + (
            self._timeout if timeout is None else float(timeout))
        keys = {}
        for col in sorted(spec):
            shape, dtype = spec[col]
            keys[col] = (col, tuple(int(d) for d in shape),
                         np.dtype(dtype).str)
        while True:
            with self._cv:
                missing = self._missing_buckets(keys, spec)
                if missing is None:
                    return None
                if not missing:
                    lease = self._try_grab(keys, stats)
                    if lease is not None:
                        return lease
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0 or not self._cv.wait(remaining):
                        return None
                    continue
            # allocate OUTSIDE the lock: np.zeros of a 256MB slot must not
            # stall concurrent acquire/release on the shared pool
            built = [(key, _SlotBucket(shape, dtype, self._nbuf))
                     for key, shape, dtype, _ in missing]
            protect = frozenset(keys.values())
            with self._cv:
                for key, bucket in built:
                    if key in self._buckets:
                        continue  # racing thread built it first; drop ours
                    if not self._make_room(bucket.nbytes, protect):
                        return None
                    self._buckets[key] = bucket
                self._cv.notify_all()
            # loop: grab under the lock now that the buckets exist

    def _try_grab(self, keys: Dict[str, Tuple],
                  stats) -> Optional[SlotLease]:
        """Under self._cv: all-or-nothing lease over one free buffer per
        key. None when any bucket has no free buffer (or two columns
        collapse onto one bucket)."""
        buckets = {col: self._buckets[key] for col, key in keys.items()}
        if not all(b.free for b in buckets.values()) or \
                len({id(b) for b in buckets.values()}) != len(buckets):
            return None
        self._tick += 1
        held = []
        arrays = {}
        for col, key in keys.items():
            bucket = buckets[col]
            bucket.tick = self._tick
            idx = bucket.free.pop()
            held.append((key, idx))
            arrays[col] = bucket.bufs[idx]
        return SlotLease(self, held, arrays, stats)

    def _release(self, held: List[Tuple[Tuple, int]]) -> None:
        with self._cv:
            for key, idx in held:
                bucket = self._buckets.get(key)
                if bucket is not None and idx not in bucket.free:
                    bucket.free.append(idx)
            self._cv.notify_all()

    def _note_fill(self, held: List[Tuple[Tuple, int]],
                   interval: Tuple[float, float]) -> None:
        """Record a completed fill on the lease's OWN buckets only: overlap
        must measure this bucket-pair's double buffering, not unrelated
        leases elsewhere in the shared pool."""
        with self._cv:
            for key, _idx in held:
                bucket = self._buckets.get(key)
                if bucket is not None:
                    bucket.fills.append(interval)

    def _overlap(self, held: List[Tuple[Tuple, int]],
                 tx0: float, tx1: float) -> float:
        """Seconds of [tx0, tx1] overlapped by sibling fills in the lease's
        own buckets (a lease's own fill ends before its transfer begins, so
        it contributes zero by construction). Multi-column leases record
        one identical interval per bucket — deduped so it counts once."""
        with self._cv:
            fills = {f for key, _idx in held
                     for f in getattr(self._buckets.get(key), "fills", ())}
        return sum(max(0.0, min(tx1, f1) - max(tx0, f0))
                   for f0, f1 in fills)

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            buckets = len(self._buckets)
            buffers = sum(len(b.bufs) for b in self._buckets.values())
            nbytes = sum(b.nbytes for b in self._buckets.values())
            evictions = self._evictions
        return {"buckets": buckets, "buffers": buffers, "bytes": nbytes,
                "max_total_bytes": self._max_total,
                "evictions": evictions}


def _tree_rows(item: Any) -> int:
    """Valid rows in a batch: Batch.num_valid when present, else the leading
    dim of a raw array batch."""
    nv = getattr(item, "num_valid", None)
    if nv is not None:
        return int(nv)
    shape = getattr(item, "shape", None)
    if shape:
        return int(shape[0])
    return 0


def _tree_padded(item: Any) -> int:
    """Padded (bucket) size of a batch: ``len(mask)`` of a
    parallel.batching.Batch (mask length == static batch size), 0 when the
    item carries no padding information (raw arrays are unpadded)."""
    mask = getattr(item, "mask", None)
    if mask is not None and getattr(item, "num_valid", None) is not None:
        try:
            return int(len(mask))
        except TypeError:
            return 0
    return 0


def _tree_nbytes(item: Any) -> int:
    """Total nbytes of arrays inside an arbitrary batch structure."""
    if hasattr(item, "nbytes"):
        return int(item.nbytes)
    if hasattr(item, "arrays"):  # parallel.batching.Batch
        return _tree_nbytes(item.arrays)
    if isinstance(item, dict):
        return sum(_tree_nbytes(v) for v in item.values())
    if isinstance(item, (list, tuple)):
        return sum(_tree_nbytes(v) for v in item)
    return 0


def timed_stage(put: Optional[Callable], item: Any,
                obs: Optional[tuple] = None) -> Tuple[Any, "BatchTiming"]:
    """Stage one host batch toward the device with ingest accounting: fires
    the INGEST_H2D chaos seam, runs ``put`` (the H2D transfer), blocks until
    the staged arrays are device-resident, and returns (staged, timing) with
    ``h2d_s`` filled. The single staging primitive shared by TransferRing's
    producer thread and the serving executor's fused submit path
    (core/fusion.py ``SegmentExecutor.submit_run``).

    ``obs``: optional (Tracer, sampled contexts) pair — the serving batch's
    trace binding (obs.trace.current_batch), captured by the CALLER on the
    transform thread because this often runs on the ring's producer thread,
    which does not inherit the contextvar. When set, the H2D transfer is
    recorded as an ``h2d`` span on every traced request in the batch."""
    timing = BatchTiming(bytes_in=_tree_nbytes(item), rows=_tree_rows(item),
                         padded_rows=_tree_padded(item))
    # slot-staged batches (SlotPool) carry their lease: the transfer window
    # is recorded for the per-slot overlap metric and the buffer returns to
    # the pool the moment the staged arrays are device-resident
    slot = getattr(item, "staging", None)
    t_wall = time.time()
    t0 = time.perf_counter()
    if slot is not None:
        slot.transfer_begin()
    try:
        # chaos seam: an injected delay here shows up in h2d_s (slow link),
        # an injected exception surfaces at the consumer (transfer failure)
        faults.fire(faults.INGEST_H2D, rows=timing.rows,
                    nbytes=timing.bytes_in)
        staged = put(item) if put is not None else item
        if slot is not None and _h2d_aliases_host():
            # CPU backends alias aligned host buffers on device_put: the
            # "device" array IS the slot. Releasing the slot then would let
            # the next fill corrupt a pending dispatch. A device-side copy
            # (this backend's stand-in for the DMA real accelerators do)
            # makes the staged value independent before the slot returns.
            staged = _device_copy(staged)
        _block_ready(staged)
    except BaseException:
        if slot is not None:
            # abandon: free the buffers without recording a cycle — the
            # slot is reused (overwritten) later, its content never read
            slot.release()
        raise
    timing.h2d_s = time.perf_counter() - t0
    if slot is not None:
        slot.transfer_end()
    if obs is not None:
        tracer, ctxs = obs
        tracer.record_batch("h2d", ctxs, t_wall, timing.h2d_s,
                            bytes=timing.bytes_in, rows=timing.rows)
    return staged, timing


# ---------------------------------------------------------------------------
# TransferRing
# ---------------------------------------------------------------------------


class TransferRing:
    """N-slot host->device->compute->host pipeline over an iterator of
    batches, draining results IN ORDER.

    Stage contract (each arbitrary pytrees between stages):

      - ``put(item)``    host batch -> staged device input. Runs on the
                         prefetch thread, so its H2D overlaps the consumer's
                         dispatch/drain; the ring additionally blocks the
                         producer thread until the staged arrays are ready,
                         which (a) makes ``h2d_s`` a real transfer time and
                         (b) paces the producer at link speed instead of
                         queueing unbounded device memory.
      - ``step(staged)`` dispatch the compiled computation; returns a handle
                         (device arrays + any metadata). Must not block —
                         jax dispatch is async.
      - ``fetch(handle)`` blocking readback -> the item the ring yields.

    ``depth`` bounds dispatched-but-undrained steps (the old hardwired
    2-deep ``in_flight`` list generalized); ``prefetch`` bounds staged
    batches waiting between put and step (defaults to ``depth``).

    Replaces the reference's background-thread batcher pair
    (stages/Batchers.scala:12-160) as the single overlap primitive shared by
    DNN eval, GBDT scoring, and bench. Iterate once; ``close()`` (idempotent,
    called by ``__iter__``'s finally) releases the producer thread mid-stream
    without stranding it on the bounded queue.
    """

    def __init__(self, it: Iterator, put: Optional[Callable] = None,
                 step: Optional[Callable] = None,
                 fetch: Optional[Callable] = None,
                 depth: int = 2, prefetch: Optional[int] = None,
                 stats: Optional[IngestStats] = None):
        if depth <= 0:
            raise ValueError("depth must be positive")
        self.depth = depth
        self.stats = stats if stats is not None else IngestStats()
        if hasattr(self.stats, "note_ring"):
            self.stats.note_ring(depth)
        self._step = step if step is not None else (lambda x: x)
        self._fetch = fetch if fetch is not None else _default_fetch
        self._user_put = put

        # capture the serving batch's trace binding HERE (the ring is built
        # on the transform thread, inside obs.trace.batch_context); the
        # producer thread the prefetcher spawns would see an empty context
        from ..obs.trace import current_batch

        obs = current_batch()
        self._prefetch = DevicePrefetcher(
            it, put=lambda item: timed_stage(put, item, obs=obs),
            depth=max(1, prefetch or depth))

    def close(self) -> None:
        self._prefetch.close()

    def __iter__(self):
        inflight: "deque" = deque()
        src = iter(self._prefetch)
        wall0 = time.perf_counter()
        try:
            while True:
                tq = time.perf_counter()
                try:
                    staged, timing = next(src)
                except StopIteration:
                    break
                timing.queue_s = time.perf_counter() - tq
                td = time.perf_counter()
                handle = self._step(staged)
                timing.dispatch_s = time.perf_counter() - td
                inflight.append((handle, timing))
                if hasattr(self.stats, "note_occupancy"):
                    self.stats.note_occupancy(len(inflight))
                if len(inflight) >= self.depth:
                    yield self._drain(inflight)
            while inflight:
                yield self._drain(inflight)
        finally:
            self.stats.add_wall(time.perf_counter() - wall0)
            self.close()

    def _drain(self, inflight: "deque"):
        handle, timing = inflight.popleft()
        t0 = time.perf_counter()
        _block_ready(handle)
        t1 = time.perf_counter()
        timing.compute_s = t1 - t0
        out = self._fetch(handle)
        timing.readback_s = time.perf_counter() - t1
        self.stats.record(timing)
        return out


#: lazily probed: does this backend's device_put ALIAS aligned host numpy
#: buffers instead of copying? (jax CPU does, real accelerators do not)
_H2D_ALIASES: Optional[bool] = None


def _h2d_aliases_host() -> bool:
    """One-shot probe of the default backend: stage an aligned buffer,
    mutate the host side, and see whether the device value changed. True
    means slot buffers must be device-copied before reuse."""
    global _H2D_ALIASES
    if _H2D_ALIASES is None:
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            _H2D_ALIASES = False
        else:
            try:
                # analysis: allow D001 -- one-shot probe, not per batch
                raw = np.zeros(1024 + 16, dtype=np.float32)
                off = (-raw.ctypes.data // 4) % 16  # 64-byte-align the view
                probe = raw[off:off + 512]
                dev = jax.block_until_ready(jax.device_put(probe))
                probe[0] = 1.0
                _H2D_ALIASES = bool(np.asarray(dev)[0] == 1.0)
            except Exception:  # noqa: BLE001 — assume the unsafe answer
                _H2D_ALIASES = True
    return _H2D_ALIASES


def _device_copy(tree: Any) -> Any:
    """Device-side copy of every jax array in ``tree`` (structure
    preserved) — detaches staged values from the host slot they may
    alias."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return tree

    def one(v):
        if isinstance(v, jax.Array):
            return v.copy()
        return v

    return jax.tree_util.tree_map(
        one, tree, is_leaf=lambda v: isinstance(v, jax.Array))


def _block_ready(tree: Any) -> Any:
    """Wait for every jax array in ``tree``; no-op for host-only values
    (keeps the ring usable before jax is imported / with numpy stages)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return tree
    try:
        return jax.block_until_ready(tree)
    except Exception:
        return tree


def _default_fetch(handle: Any) -> Any:
    """Readback: device arrays -> numpy, structure preserved."""
    import sys

    jax = sys.modules.get("jax")

    def one(v):
        if jax is not None and isinstance(v, jax.Array):
            return np.asarray(v)
        return v

    if isinstance(handle, tuple):
        return tuple(one(v) for v in handle)
    if isinstance(handle, list):
        return [one(v) for v in handle]
    if isinstance(handle, dict):
        return {k: one(v) for k, v in handle.items()}
    return one(handle)
