"""Unified device-ingest layer: uint8 wire format + transfer ring + stats.

The framework's data plane. BENCH_r05 showed the flagship featurize path
computing at ~11.5k images/sec/chip per-call but only ~260 images/sec
end-to-end: the DataFrame -> device ingest path, not XLA compute, was the
bottleneck (h2d_gbps = 0.036). Two structural fixes live here:

  - **uint8 on the wire** (``PreprocessSpec``): the host stops doing
    ``astype(float32) * scale`` (+ layout transpose) per image; batches ship
    in their decoded dtype (uint8 pixels = 4x fewer H2D bytes) and the
    cast/scale/transpose runs INSIDE the consumer's jitted forward, where
    XLA fuses it with the first conv's bf16 cast for free.
  - **transfer ring** (``TransferRing``): a configurable number of in-flight
    batches replaces ad-hoc double buffering. H2D runs on a background
    thread (overlapping the previous batch's compute), up to ``depth``
    dispatched steps stay in flight, and results drain in order. Every
    stage is timed per batch into an ``IngestStats`` object, so the
    e2e-vs-per-call gap is a first-class measured quantity.

Consumers: DNNModel (models/dnn_model.py) for the DataFrame eval path,
DeviceEnsemble (gbdt/predict.py) for chunked GBDT scoring, and bench.py's
e2e section. The ring is generic — anything shaped
``host batches -> stage -> dispatch -> readback`` can ride it.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core import faults
from .batching import DevicePrefetcher


# ---------------------------------------------------------------------------
# PreprocessSpec: host preprocessing moved into the compiled forward
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PreprocessSpec:
    """Device-side preprocessing fused into a jitted forward.

    Describes what the host USED to do to each row before batching —
    ``astype(float32) * scale + offset`` and an optional per-row axes
    transpose (NHWC -> NCHW for ONNX imports) — so the wire carries the raw
    decoded dtype and the work runs on device, inside jit. Hashable, so
    compiled-forward caches can key on it.

    ``transpose`` is the PER-ROW axes permutation (e.g. ``(2, 0, 1)`` for
    HWC -> CHW); the batched device op shifts it past the leading batch dim.
    ``dtype``: compute dtype after the cast (float32 unless doing f64
    numerics experiments).
    """

    scale: float = 1.0
    offset: float = 0.0
    transpose: Optional[Tuple[int, ...]] = None
    dtype: str = "float32"

    def __post_init__(self):
        if self.transpose is not None:
            object.__setattr__(self, "transpose",
                               tuple(int(a) for a in self.transpose))

    @property
    def is_identity(self) -> bool:
        return (self.scale == 1.0 and self.offset == 0.0
                and self.transpose is None and self.dtype == "float32")

    def _batch_axes(self, ndim: int) -> Tuple[int, ...]:
        perm = self.transpose
        if perm is None or len(perm) != ndim - 1:
            raise ValueError(
                f"transpose {perm} does not match per-row rank {ndim - 1}")
        return (0,) + tuple(a + 1 for a in perm)

    def apply_device(self, x):
        """Batched [B, ...] device op, trace-safe under jit."""
        import jax.numpy as jnp

        dt = getattr(jnp, self.dtype)
        y = x.astype(dt)
        if self.scale != 1.0:
            y = y * dt(self.scale)
        if self.offset != 0.0:
            y = y + dt(self.offset)
        if self.transpose is not None:
            y = jnp.transpose(y, self._batch_axes(y.ndim))
        return y

    def apply_host(self, x: np.ndarray) -> np.ndarray:
        """Numpy reference of ``apply_device`` on a [B, ...] batch — the
        numerical-parity oracle (uint8 -> f32 cast and an f32 multiply are
        exact, so host and device agree bitwise) and the fallback for
        consumers that never reach a device."""
        dt = np.dtype(self.dtype).type
        y = x.astype(dt)
        if self.scale != 1.0:
            y = y * dt(self.scale)
        if self.offset != 0.0:
            y = y + dt(self.offset)
        if self.transpose is not None:
            y = np.transpose(y, self._batch_axes(y.ndim))
        return y

    def apply_host_row(self, img: np.ndarray) -> np.ndarray:
        """Per-row host application (the legacy featurizer prep path)."""
        return self.apply_host(img[None])[0]


# ---------------------------------------------------------------------------
# IngestStats: per-batch ingest decomposition
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BatchTiming:
    """Wall-clock decomposition of one batch through the ring (seconds).

    ``queue_s``  — consumer wait for the prefetched batch (producer-bound
                   time: decode/stack upstream plus H2D not yet hidden).
    ``h2d_s``    — host->device transfer, measured ON the producer thread
                   (device_put + block-until-ready), so it overlaps compute.
    ``dispatch_s`` — host cost of enqueueing the compiled step (async).
    ``compute_s``  — residual wait for the step's outputs at drain time
                   (0 when compute fully hid behind later batches' ingest).
    ``readback_s`` — device->host fetch of the outputs.
    ``bytes_in`` — wire bytes shipped for this batch.
    ``rows``     — valid rows in the batch.
    ``padded_rows`` — the static bucket size the batch was padded to (0 =
                   unpadded/unknown); ``padded_rows - rows`` is pure
                   pad-waste compute, the cost-model term the bucket
                   auto-tuner (core/costmodel.py) minimizes.
    """

    queue_s: float = 0.0
    h2d_s: float = 0.0
    dispatch_s: float = 0.0
    compute_s: float = 0.0
    readback_s: float = 0.0
    bytes_in: int = 0
    rows: int = 0
    padded_rows: int = 0


class IngestStats:
    """Accumulates ``BatchTiming`` rows plus ring wall time; ``summary()``
    renders the e2e decomposition bench.py and the serving stats endpoint
    surface. Safe to share across sequential ring runs (partitions of one
    transform accumulate into one object)."""

    def __init__(self):
        self.records: List[BatchTiming] = []
        self.wall_s: float = 0.0
        # ring slot occupancy (dispatched-but-undrained steps): configured
        # depth + running mean/max of observed fill, so "is the ring ever
        # actually full?" is a scraped gauge instead of a rerun experiment
        self.ring_depth: int = 0
        self._occ_sum: int = 0
        self._occ_n: int = 0
        self._occ_max: int = 0
        # pad-waste per bucket: {padded size: [batches, real rows]} — the
        # measured term behind mmlspark_batch_pad_ratio{bucket=} and the
        # cost model's bucket chooser (assumed-waste becomes measured-waste)
        self._pad: Dict[int, List[int]] = {}

    def record(self, t: BatchTiming) -> None:
        self.records.append(t)
        if t.padded_rows > 0:
            self.note_padding(t.padded_rows, t.rows)

    def note_padding(self, bucket: int, rows: int) -> None:
        """Count one batch padded to ``bucket`` static rows with ``rows``
        real ones (callable directly by batchers outside the ring)."""
        acc = self._pad.setdefault(int(bucket), [0, 0])
        acc[0] += 1
        acc[1] += int(rows)

    def add_wall(self, seconds: float) -> None:
        self.wall_s += seconds

    def note_ring(self, depth: int) -> None:
        self.ring_depth = max(self.ring_depth, int(depth))

    def note_occupancy(self, in_flight: int) -> None:
        n = int(in_flight)
        self._occ_sum += n
        self._occ_n += 1
        self._occ_max = max(self._occ_max, n)

    def merge(self, other: "IngestStats") -> None:
        """Fold another stats object in (segment aggregation)."""
        self.records.extend(other.records)
        self.wall_s += other.wall_s
        self.ring_depth = max(self.ring_depth, other.ring_depth)
        self._occ_sum += other._occ_sum
        self._occ_n += other._occ_n
        self._occ_max = max(self._occ_max, other._occ_max)
        for bucket, (batches, rows) in other._pad.items():
            acc = self._pad.setdefault(bucket, [0, 0])
            acc[0] += batches
            acc[1] += rows

    @property
    def num_batches(self) -> int:
        return len(self.records)

    def _pad_summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        padding: Dict[str, Any] = {}
        tot_real = tot_padded = 0
        for bucket in sorted(self._pad):
            batches, real = self._pad[bucket]
            padded = batches * bucket
            tot_real += real
            tot_padded += padded
            padding[str(bucket)] = {
                "batches": batches, "rows": real, "padded": padded,
                # fraction of the bucket's compute spent on pad rows
                "pad_ratio": round(1 - real / padded, 4) if padded
                else None}
        out["padding"] = padding
        if tot_padded:
            out["pad_ratio"] = round(1 - tot_real / tot_padded, 4)
        return out

    def summary(self) -> Dict[str, Any]:
        if not self.records:
            out = {"n_batches": 0}
            if self._pad:
                out.update(self._pad_summary())
            return out
        cols = {f: float(sum(getattr(r, f) for r in self.records))
                for f in ("queue_s", "h2d_s", "dispatch_s", "compute_s",
                          "readback_s")}
        total_bytes = int(sum(r.bytes_in for r in self.records))
        rows = int(sum(r.rows for r in self.records))
        serial = sum(cols.values())
        n = len(self.records)
        out: Dict[str, Any] = {
            "n_batches": n,
            "rows": rows,
            "bytes": total_bytes,
            "wall_s": round(self.wall_s, 6),
            # < 1.0 means the ring hid ingest behind compute (and vice
            # versa); 1.0 = fully serial pipeline
            "overlap_ratio": round(self.wall_s / serial, 4) if serial > 0
            else None,
            "h2d_gbps": round(total_bytes / cols["h2d_s"] / 1e9, 4)
            if cols["h2d_s"] > 0 else None,
        }
        if self.ring_depth > 0:
            out["ring_depth"] = self.ring_depth
            if self._occ_n > 0:
                out["ring_occupancy_mean"] = round(
                    self._occ_sum / self._occ_n, 4)
                out["ring_occupancy_max"] = self._occ_max
        if self._pad:
            out.update(self._pad_summary())
        for f, v in cols.items():
            out[f] = round(v, 6)
            out[f"{f[:-2]}_ms_per_batch"] = round(v / n * 1e3, 4)
        return out


def rows_to_batch(rows) -> np.ndarray:
    """Per-row arrays -> one contiguous [B, ...] batch for H2D staging.

    The binary-wire ingest path: ``decode_frame`` hands each request's
    payload back as a zero-copy VIEW over its body bytes, and this is the
    single host copy that remains — the batch stack that doubles as the
    transfer ring's staging buffer (uint8 on the wire, cast/scale on
    device via PreprocessSpec).

    Fast path: when the rows are adjacent views over ONE buffer (a client
    shipped a whole batch in one frame column, or journal replay of a
    concatenated region), the batch is a strided view — zero copies
    end-to-end. Otherwise ``np.stack``. Rows must agree on shape and dtype
    (ragged batches stay on the per-row host path)."""
    arrs = [np.asarray(r) for r in rows]
    if not arrs:
        raise ValueError("rows_to_batch needs at least one row")
    shape, dt = arrs[0].shape, arrs[0].dtype
    for a in arrs[1:]:
        if a.shape != shape or a.dtype != dt:
            raise ValueError(
                f"ragged batch: {a.shape}/{a.dtype} vs {shape}/{dt}")
    if len(arrs) == 1:
        return arrs[0][None] if arrs[0].flags["C_CONTIGUOUS"] \
            else np.ascontiguousarray(arrs[0])[None]
    nb = arrs[0].nbytes
    if nb and all(a.flags["C_CONTIGUOUS"] for a in arrs):
        try:
            ptr0 = arrs[0].__array_interface__["data"][0]
            adjacent = all(
                a.__array_interface__["data"][0] == ptr0 + i * nb
                for i, a in enumerate(arrs))
        except (KeyError, TypeError):
            adjacent = False
        if adjacent:
            # one spanning view over the shared buffer; arrs[0] rides along
            # as .base so the underlying memory stays alive
            return np.lib.stride_tricks.as_strided(
                arrs[0], shape=(len(arrs),) + shape,
                strides=(nb,) + arrs[0].strides)
    return np.stack(arrs)


def _tree_rows(item: Any) -> int:
    """Valid rows in a batch: Batch.num_valid when present, else the leading
    dim of a raw array batch."""
    nv = getattr(item, "num_valid", None)
    if nv is not None:
        return int(nv)
    shape = getattr(item, "shape", None)
    if shape:
        return int(shape[0])
    return 0


def _tree_padded(item: Any) -> int:
    """Padded (bucket) size of a batch: ``len(mask)`` of a
    parallel.batching.Batch (mask length == static batch size), 0 when the
    item carries no padding information (raw arrays are unpadded)."""
    mask = getattr(item, "mask", None)
    if mask is not None and getattr(item, "num_valid", None) is not None:
        try:
            return int(len(mask))
        except TypeError:
            return 0
    return 0


def _tree_nbytes(item: Any) -> int:
    """Total nbytes of arrays inside an arbitrary batch structure."""
    if hasattr(item, "nbytes"):
        return int(item.nbytes)
    if hasattr(item, "arrays"):  # parallel.batching.Batch
        return _tree_nbytes(item.arrays)
    if isinstance(item, dict):
        return sum(_tree_nbytes(v) for v in item.values())
    if isinstance(item, (list, tuple)):
        return sum(_tree_nbytes(v) for v in item)
    return 0


def timed_stage(put: Optional[Callable], item: Any,
                obs: Optional[tuple] = None) -> Tuple[Any, "BatchTiming"]:
    """Stage one host batch toward the device with ingest accounting: fires
    the INGEST_H2D chaos seam, runs ``put`` (the H2D transfer), blocks until
    the staged arrays are device-resident, and returns (staged, timing) with
    ``h2d_s`` filled. The single staging primitive shared by TransferRing's
    producer thread and the serving executor's fused submit path
    (core/fusion.py ``SegmentExecutor.submit_run``).

    ``obs``: optional (Tracer, sampled contexts) pair — the serving batch's
    trace binding (obs.trace.current_batch), captured by the CALLER on the
    transform thread because this often runs on the ring's producer thread,
    which does not inherit the contextvar. When set, the H2D transfer is
    recorded as an ``h2d`` span on every traced request in the batch."""
    timing = BatchTiming(bytes_in=_tree_nbytes(item), rows=_tree_rows(item),
                         padded_rows=_tree_padded(item))
    t_wall = time.time()
    t0 = time.perf_counter()
    # chaos seam: an injected delay here shows up in h2d_s (slow link), an
    # injected exception surfaces at the consumer (transfer failure)
    faults.fire(faults.INGEST_H2D, rows=timing.rows, nbytes=timing.bytes_in)
    staged = put(item) if put is not None else item
    _block_ready(staged)
    timing.h2d_s = time.perf_counter() - t0
    if obs is not None:
        tracer, ctxs = obs
        tracer.record_batch("h2d", ctxs, t_wall, timing.h2d_s,
                            bytes=timing.bytes_in, rows=timing.rows)
    return staged, timing


# ---------------------------------------------------------------------------
# TransferRing
# ---------------------------------------------------------------------------


class TransferRing:
    """N-slot host->device->compute->host pipeline over an iterator of
    batches, draining results IN ORDER.

    Stage contract (each arbitrary pytrees between stages):

      - ``put(item)``    host batch -> staged device input. Runs on the
                         prefetch thread, so its H2D overlaps the consumer's
                         dispatch/drain; the ring additionally blocks the
                         producer thread until the staged arrays are ready,
                         which (a) makes ``h2d_s`` a real transfer time and
                         (b) paces the producer at link speed instead of
                         queueing unbounded device memory.
      - ``step(staged)`` dispatch the compiled computation; returns a handle
                         (device arrays + any metadata). Must not block —
                         jax dispatch is async.
      - ``fetch(handle)`` blocking readback -> the item the ring yields.

    ``depth`` bounds dispatched-but-undrained steps (the old hardwired
    2-deep ``in_flight`` list generalized); ``prefetch`` bounds staged
    batches waiting between put and step (defaults to ``depth``).

    Replaces the reference's background-thread batcher pair
    (stages/Batchers.scala:12-160) as the single overlap primitive shared by
    DNN eval, GBDT scoring, and bench. Iterate once; ``close()`` (idempotent,
    called by ``__iter__``'s finally) releases the producer thread mid-stream
    without stranding it on the bounded queue.
    """

    def __init__(self, it: Iterator, put: Optional[Callable] = None,
                 step: Optional[Callable] = None,
                 fetch: Optional[Callable] = None,
                 depth: int = 2, prefetch: Optional[int] = None,
                 stats: Optional[IngestStats] = None):
        if depth <= 0:
            raise ValueError("depth must be positive")
        self.depth = depth
        self.stats = stats if stats is not None else IngestStats()
        if hasattr(self.stats, "note_ring"):
            self.stats.note_ring(depth)
        self._step = step if step is not None else (lambda x: x)
        self._fetch = fetch if fetch is not None else _default_fetch
        self._user_put = put

        # capture the serving batch's trace binding HERE (the ring is built
        # on the transform thread, inside obs.trace.batch_context); the
        # producer thread the prefetcher spawns would see an empty context
        from ..obs.trace import current_batch

        obs = current_batch()
        self._prefetch = DevicePrefetcher(
            it, put=lambda item: timed_stage(put, item, obs=obs),
            depth=max(1, prefetch or depth))

    def close(self) -> None:
        self._prefetch.close()

    def __iter__(self):
        inflight: "deque" = deque()
        src = iter(self._prefetch)
        wall0 = time.perf_counter()
        try:
            while True:
                tq = time.perf_counter()
                try:
                    staged, timing = next(src)
                except StopIteration:
                    break
                timing.queue_s = time.perf_counter() - tq
                td = time.perf_counter()
                handle = self._step(staged)
                timing.dispatch_s = time.perf_counter() - td
                inflight.append((handle, timing))
                if hasattr(self.stats, "note_occupancy"):
                    self.stats.note_occupancy(len(inflight))
                if len(inflight) >= self.depth:
                    yield self._drain(inflight)
            while inflight:
                yield self._drain(inflight)
        finally:
            self.stats.add_wall(time.perf_counter() - wall0)
            self.close()

    def _drain(self, inflight: "deque"):
        handle, timing = inflight.popleft()
        t0 = time.perf_counter()
        _block_ready(handle)
        t1 = time.perf_counter()
        timing.compute_s = t1 - t0
        out = self._fetch(handle)
        timing.readback_s = time.perf_counter() - t1
        self.stats.record(timing)
        return out


def _block_ready(tree: Any) -> Any:
    """Wait for every jax array in ``tree``; no-op for host-only values
    (keeps the ring usable before jax is imported / with numpy stages)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return tree
    try:
        return jax.block_until_ready(tree)
    except Exception:
        return tree


def _default_fetch(handle: Any) -> Any:
    """Readback: device arrays -> numpy, structure preserved."""
    import sys

    jax = sys.modules.get("jax")

    def one(v):
        if jax is not None and isinstance(v, jax.Array):
            return np.asarray(v)
        return v

    if isinstance(handle, tuple):
        return tuple(one(v) for v in handle)
    if isinstance(handle, list):
        return [one(v) for v in handle]
    if isinstance(handle, dict):
        return {k: one(v) for k, v in handle.items()}
    return one(handle)
