"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pipe``
mesh axis.

The reference scales depth-wise only via Spark's row partitioning (all
executors hold the whole model); on TPU, models that exceed one chip's HBM
shard by LAYERS across the ``pipe`` axis. This module implements the classic
collective-permute pipeline (the scaling-book / shard_map-tutorial schedule):

  - stage ``s`` holds segment ``s`` of the layer stack (params stacked with
    a leading [S] dim sharded over ``pipe``);
  - time runs for ``M + S - 1`` ticks; at tick ``t`` every stage applies its
    segment to its current activation, then activations shift one hop to the
    next stage via ``ppermute`` (ICI neighbor traffic only);
  - stage 0 feeds microbatch ``t`` while stage ``S-1`` emits finished
    microbatch ``t-(S-1)`` — the steady state keeps every chip busy; the
    bubble is the usual ``(S-1)/(M+S-1)`` fraction.

``pipeline_apply`` is functional and grad-safe (ppermute has a transpose
rule, so ``jax.grad`` through the pipeline yields the backward schedule).
"""

from __future__ import annotations

from typing import Any, Callable

__all__ = ["pipeline_apply", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """[params_stage0, params_stage1, ...] (identical treedefs) -> one pytree
    with a leading [S] dim on every leaf — the layout pipeline_apply expects,
    sharded over the pipe axis via P('pipe', ...)."""
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda *ls: jnp.stack(ls), *per_stage_params)


def pipeline_apply(stage_fn: Callable[[Any, Any], Any], stacked_params,
                   microbatches, axis_name: str, axis_size: int):
    """Run microbatches through the stage pipeline.

    Args:
      stage_fn: ``(stage_params, x) -> y`` for ONE stage segment; activation
        shapes must be identical across stages (uniform residual width).
      stacked_params: pytree with leading [S] dim per leaf; inside shard_map
        each device sees its local [1, ...] slice (S sharded over
        ``axis_name``).
      microbatches: [M, ...] array of microbatch inputs, replicated.
      axis_name/axis_size: the pipe mesh axis and its (static) size.

    Returns [M, ...] outputs (valid on every device after the final psum-
    style broadcast from the last stage).

    Call under ``jax.shard_map`` with ``in_specs=(P('pipe'), P(), ...)``:

        out = shard_map(
            lambda p, xs: pipeline_apply(stage_fn, p, xs, 'pipe', S),
            mesh=mesh, in_specs=(P('pipe'), P()), out_specs=P())(params, xs)
    """
    import jax
    import jax.numpy as jnp

    S = axis_size
    M = microbatches.shape[0]
    stage = jax.lax.axis_index(axis_name)
    local = jax.tree.map(lambda a: a[0], stacked_params)  # [1,...] -> [...]

    # jax < 0.5 has neither pcast nor pvary (and no vma typing to satisfy)
    if hasattr(jax.lax, "pcast"):
        # analysis: allow J001 -- hasattr-guarded on the line above: this IS the gate
        microbatches = jax.lax.pcast(microbatches, (axis_name,), to="varying")
    elif hasattr(jax.lax, "pvary"):
        # analysis: allow J001 -- hasattr-guarded on the line above: this IS the gate
        microbatches = jax.lax.pvary(microbatches, (axis_name,))
    # derived arrays inherit the varying type from microbatches
    state = jnp.zeros_like(microbatches[0])
    outputs = jnp.zeros_like(microbatches)
    shift = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clamped; masked out past M)
        feed = microbatches[jnp.minimum(t, M - 1)]
        x = jnp.where(stage == 0, feed, state)
        y = stage_fn(local, x)
        # last stage emits finished microbatch t-(S-1)
        out_idx = t - (S - 1)
        emit = jnp.logical_and(stage == S - 1, out_idx >= 0)
        outputs = jax.lax.cond(
            emit,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), 0),
            lambda o: o, outputs)
        # activations hop to the next stage (wraparound hop is ignored by
        # stage 0, which reads fresh microbatches instead)
        state = jax.lax.ppermute(y, axis_name, shift)
        return (state, outputs), None

    (_, outputs), _ = jax.lax.scan(
        tick, (state, outputs), jnp.arange(M + S - 1))
    # broadcast the last stage's collected outputs to every device
    last = jnp.equal(stage, S - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * last, axis_name)
