"""Device mesh runtime: discovery, construction, topology.

Replaces the reference's cluster-topology layer (core/utils/ClusterUtil.scala:13-90 —
executor/core counting from BlockManager state; lightgbm/LightGBMUtils.scala:105-173 —
driver-socket rendezvous) with the TPU-native equivalents:

  - device discovery         = jax.devices()
  - rendezvous               = jax.distributed.initialize (multi-host; ICI needs none)
  - worker count             = mesh axis sizes
  - barrier gang start       = SPMD launch (inherent on TPU pods)

Standard axis names follow the scaling-book convention: ``data`` (DP over ICI/DCN),
``fsdp`` (param sharding), ``tensor`` (TP), ``seq`` (sequence/context parallel),
``expert`` (EP). Single-chip meshes are 1-sized on every axis, so all code paths are
mesh-agnostic.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("mmlspark_tpu")

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tensor"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"


def devices(backend: Optional[str] = None) -> List:
    import jax
    return jax.devices(backend) if backend else jax.devices()


def local_device_count() -> int:
    import jax
    return jax.local_device_count()


_dist_initialized = False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Multi-host bootstrap (replaces driver-socket rendezvous,
    LightGBMUtils.scala:105-173). Called automatically by ``make_mesh`` before
    device discovery; explicit earlier calls are fine and idempotent.

    Arguments default from the environment — ``MMLSPARK_COORDINATOR``,
    ``MMLSPARK_NUM_PROCESSES``, ``MMLSPARK_PROCESS_ID`` — so a pod launch
    (one process per host, same program) needs no code changes: set the env
    on each host and every ``make_mesh()`` sees the global device set.
    No-op when single-process. Returns True iff jax.distributed was
    initialized by this call.
    """
    global _dist_initialized
    if _dist_initialized:
        return False
    coordinator_address = coordinator_address or \
        os.environ.get("MMLSPARK_COORDINATOR")
    if num_processes is None and "MMLSPARK_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["MMLSPARK_NUM_PROCESSES"])
    if process_id is None and "MMLSPARK_PROCESS_ID" in os.environ:
        process_id = int(os.environ["MMLSPARK_PROCESS_ID"])
    if num_processes in (None, 1):
        # single-process no-op does NOT latch: a later explicit call (or one
        # made after the env appears) must still be able to initialize
        return False
    import jax

    if getattr(jax.distributed, "is_initialized", lambda: False)():
        # the user bootstrapped jax.distributed themselves (standard JAX
        # multi-host practice) — respect it, don't double-initialize
        _dist_initialized = True
        return False
    jax.distributed.initialize(coordinator_address, num_processes, process_id)
    _dist_initialized = True  # latch only after a successful init
    log.info("jax.distributed initialized: process %s of %s via %s",
             process_id, num_processes, coordinator_address)
    return True


def process_shard(df, process_id: Optional[int] = None,
                  num_processes: Optional[int] = None):
    """Per-process input sharding: each host feeds its own slice of a
    DataFrame's partitions into the mesh (the SPMD input-pipeline story —
    the reference's equivalent is Spark assigning partitions to executors).
    Round-robin by partition index; identity when single-process."""
    import jax

    if process_id is None or num_processes is None:
        # env-var launches must shard correctly even before make_mesh runs
        initialize_distributed()
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if num_processes is None else num_processes
    if n <= 1:
        return df
    from ..core.dataframe import DataFrame

    mine = [p for i, p in enumerate(df.partitions) if i % n == pid]
    if not mine:
        return df.limit(0)
    return DataFrame(mine, schema=df.schema)


@dataclasses.dataclass
class MeshSpec:
    """Declarative mesh shape; -1 on one axis absorbs remaining devices."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1
    pipe: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = dataclasses.asdict(self)
        fixed = 1
        wild = None
        for k, v in sizes.items():
            if v == -1:
                if wild is not None:
                    raise ValueError("Only one mesh axis may be -1")
                wild = k
            else:
                fixed *= v
        if wild is not None:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wild] = n_devices // fixed
        else:
            total = int(np.prod(list(sizes.values())))
            if total != n_devices:
                raise ValueError(f"Mesh {sizes} needs {total} devices, have {n_devices}")
        return sizes


def make_mesh(spec: Optional[MeshSpec] = None, device_list: Optional[Sequence] = None):
    """Build a jax.sharding.Mesh over the available devices.

    Axes with size 1 are kept in the mesh (harmless; lets sharding rules name them
    unconditionally). Uses jax.make_mesh so device order follows physical topology
    (ICI-contiguous) rather than enumeration order.
    """
    import jax

    if device_list is None:
        initialize_distributed()  # env-driven multi-host bootstrap (no-op local)
    spec = spec or MeshSpec()
    devs = list(device_list) if device_list is not None else jax.devices()
    sizes = spec.resolve(len(devs))
    axis_names = tuple(sizes.keys())
    shape = tuple(sizes[a] for a in axis_names)
    # Auto axis types: GSPMD propagation (annotate shardings, XLA inserts
    # collectives) — jax>=0.9 defaults make_mesh to Explicit, which we don't want
    # for the framework's implicit-sharding style. Older jax (< 0.5) has no
    # AxisType and is always Auto — gate on the attribute, not the version.
    axis_type = getattr(jax.sharding, "AxisType", None)
    kwargs = {} if axis_type is None else \
        {"axis_types": (axis_type.Auto,) * len(axis_names)}
    if device_list is not None:
        arr = np.asarray(devs).reshape(shape)
        return jax.sharding.Mesh(arr, axis_names, **kwargs)
    return jax.make_mesh(shape, axis_names, devices=devs, **kwargs)


def shard_map_compat(f, **kwargs):
    """``jax.shard_map`` resolved across jax versions: older jax ships it
    under ``jax.experimental.shard_map`` and calls the replication-checking
    kwarg ``check_rep`` instead of ``check_vma``. Drop-in for
    ``functools.partial(shard_map, ...)`` decorator usage."""
    import inspect

    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    params = inspect.signature(fn).parameters
    if "check_vma" in kwargs and "check_vma" not in params:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in params:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return fn(f, **kwargs)


def data_sharding(mesh, *batch_axes: str):
    """NamedSharding that shards the leading (batch) dim over the data axes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    axes = batch_axes or (DATA_AXIS,)
    return NamedSharding(mesh, P(axes))


def replicated_sharding(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def num_data_shards(mesh) -> int:
    return int(mesh.shape.get(DATA_AXIS, 1) * mesh.shape.get(FSDP_AXIS, 1))


def fetch_global(x):
    """``jax.device_get`` that also works when arrays span PROCESSES (the
    multi-host counterpart of the reference's executor-to-driver collects,
    LightGBMBase.scala:157-159): fully-addressable values fetch directly —
    in single-process runs that is every value, so this is a drop-in;
    fully-replicated global arrays read the local shard; row-sharded
    global arrays allgather across processes. Collective when
    multi-process — every process must call it in lockstep (true for the
    SPMD host loops that use it)."""
    import jax

    def one(a):
        if not isinstance(a, jax.Array) or a.is_fully_addressable:
            return a
        if a.is_fully_replicated:
            return np.asarray(a.addressable_data(0))
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(a, tiled=True))

    return jax.device_get(jax.tree.map(one, x))


class MeshContext:
    """Process-wide default mesh (lazily built single-axis DP mesh).

    Stages that dispatch to devices consult this unless given an explicit mesh —
    the analogue of the reference stages consulting ClusterUtil for worker counts
    (lightgbm/LightGBMBase.scala:120-128).
    """

    _default = None
    _explicit = False

    @classmethod
    def get(cls):
        if cls._default is None:
            cls._default = make_mesh()  # lazy: does NOT count as explicit
        return cls._default

    @classmethod
    def current(cls):
        """The explicitly-set mesh (via set()), or None. A mesh that get()
        built lazily does not count. Auto-mode consumers (DNNModel
        useMesh=None) use this so that 'no mesh configured' stays
        single-device instead of silently adopting a lazily-constructed
        global-device mesh (which would span non-addressable devices in a
        multi-host deployment)."""
        return cls._default if cls._explicit else None

    @classmethod
    def set(cls, mesh) -> None:
        cls._default = mesh
        cls._explicit = True

    @classmethod
    def reset(cls) -> None:
        cls._default = None
        cls._explicit = False
