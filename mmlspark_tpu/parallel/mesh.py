"""Device mesh runtime: discovery, construction, topology.

Replaces the reference's cluster-topology layer (core/utils/ClusterUtil.scala:13-90 —
executor/core counting from BlockManager state; lightgbm/LightGBMUtils.scala:105-173 —
driver-socket rendezvous) with the TPU-native equivalents:

  - device discovery         = jax.devices()
  - rendezvous               = jax.distributed.initialize (multi-host; ICI needs none)
  - worker count             = mesh axis sizes
  - barrier gang start       = SPMD launch (inherent on TPU pods)

Standard axis names follow the scaling-book convention: ``data`` (DP over ICI/DCN),
``fsdp`` (param sharding), ``tensor`` (TP), ``seq`` (sequence/context parallel),
``expert`` (EP). Single-chip meshes are 1-sized on every axis, so all code paths are
mesh-agnostic.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("mmlspark_tpu")

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tensor"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def devices(backend: Optional[str] = None) -> List:
    import jax
    return jax.devices(backend) if backend else jax.devices()


def local_device_count() -> int:
    import jax
    return jax.local_device_count()


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap (replaces driver-socket rendezvous,
    LightGBMUtils.scala:105-173). No-op when single-process."""
    if num_processes in (None, 1):
        return
    import jax
    jax.distributed.initialize(coordinator_address, num_processes, process_id)


@dataclasses.dataclass
class MeshSpec:
    """Declarative mesh shape; -1 on one axis absorbs remaining devices."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1
    expert: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = dataclasses.asdict(self)
        fixed = 1
        wild = None
        for k, v in sizes.items():
            if v == -1:
                if wild is not None:
                    raise ValueError("Only one mesh axis may be -1")
                wild = k
            else:
                fixed *= v
        if wild is not None:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wild] = n_devices // fixed
        else:
            total = int(np.prod(list(sizes.values())))
            if total != n_devices:
                raise ValueError(f"Mesh {sizes} needs {total} devices, have {n_devices}")
        return sizes


def make_mesh(spec: Optional[MeshSpec] = None, device_list: Optional[Sequence] = None):
    """Build a jax.sharding.Mesh over the available devices.

    Axes with size 1 are kept in the mesh (harmless; lets sharding rules name them
    unconditionally). Uses jax.make_mesh so device order follows physical topology
    (ICI-contiguous) rather than enumeration order.
    """
    import jax

    spec = spec or MeshSpec()
    devs = list(device_list) if device_list is not None else jax.devices()
    sizes = spec.resolve(len(devs))
    axis_names = tuple(sizes.keys())
    shape = tuple(sizes[a] for a in axis_names)
    # Auto axis types: GSPMD propagation (annotate shardings, XLA inserts
    # collectives) — jax>=0.9 defaults make_mesh to Explicit, which we don't want
    # for the framework's implicit-sharding style.
    auto = (jax.sharding.AxisType.Auto,) * len(axis_names)
    if device_list is not None:
        arr = np.asarray(devs).reshape(shape)
        return jax.sharding.Mesh(arr, axis_names, axis_types=auto)
    return jax.make_mesh(shape, axis_names, devices=devs, axis_types=auto)


def data_sharding(mesh, *batch_axes: str):
    """NamedSharding that shards the leading (batch) dim over the data axes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    axes = batch_axes or (DATA_AXIS,)
    return NamedSharding(mesh, P(axes))


def replicated_sharding(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def num_data_shards(mesh) -> int:
    return int(mesh.shape.get(DATA_AXIS, 1) * mesh.shape.get(FSDP_AXIS, 1))


class MeshContext:
    """Process-wide default mesh (lazily built single-axis DP mesh).

    Stages that dispatch to devices consult this unless given an explicit mesh —
    the analogue of the reference stages consulting ClusterUtil for worker counts
    (lightgbm/LightGBMBase.scala:120-128).
    """

    _default = None
    _explicit = False

    @classmethod
    def get(cls):
        if cls._default is None:
            cls._default = make_mesh()  # lazy: does NOT count as explicit
        return cls._default

    @classmethod
    def current(cls):
        """The explicitly-set mesh (via set()), or None. A mesh that get()
        built lazily does not count. Auto-mode consumers (DNNModel
        useMesh=None) use this so that 'no mesh configured' stays
        single-device instead of silently adopting a lazily-constructed
        global-device mesh (which would span non-addressable devices in a
        multi-host deployment)."""
        return cls._default if cls._explicit else None

    @classmethod
    def set(cls, mesh) -> None:
        cls._default = mesh
        cls._explicit = True

    @classmethod
    def reset(cls) -> None:
        cls._default = None
        cls._explicit = False
