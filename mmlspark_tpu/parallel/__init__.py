from .batching import Batch, Minibatcher, concat_outputs, next_bucket, pad_batch, stack_rows
from .mesh import (
    DATA_AXIS, EXPERT_AXIS, FSDP_AXIS, SEQ_AXIS, TENSOR_AXIS,
    MeshContext, MeshSpec, data_sharding, make_mesh, num_data_shards, replicated_sharding,
)
