from .batching import (
    Batch, Minibatcher, concat_outputs, densify_sparse, is_sparse_row,
    next_bucket, pad_batch, sparse_width, stack_rows,
)
from .ingest import IngestStats, PreprocessSpec, TransferRing
from .mesh import (
    DATA_AXIS, EXPERT_AXIS, FSDP_AXIS, PIPE_AXIS, SEQ_AXIS, TENSOR_AXIS,
    MeshContext, MeshSpec, data_sharding, initialize_distributed, make_mesh,
    num_data_shards, process_shard, replicated_sharding,
)
from .pipeline_parallel import pipeline_apply, stack_stage_params
