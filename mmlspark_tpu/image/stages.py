"""Image pipeline stages (ImageTransformer / Resize / Unroll / Augment parity).

All stages read/write ImageSchema struct columns (core/schema.py) — per-row dicts of
{origin, height, width, nChannels, mode, data} with an HWC numpy array payload.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.device_stage import DeviceFn, FusionUnsupported
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import ColType, ImageSchema, Schema
from ..ops import image as ops

def _f32_exact(v) -> bool:
    """True when ``float(v)`` round-trips through float32 unchanged — the
    precondition for host-f64 scalar arithmetic (numpy promotes python
    floats to f64) to agree bitwise with the device's f32 compute."""
    try:
        return float(np.float32(v)) == float(v)
    except (TypeError, ValueError, OverflowError):
        return False


def _op_device_exact(op) -> bool:
    """Image ops with a bitwise-exact batched device mirror (ops/image.py).

    resize/blur/gaussianKernel compute through f64 interpolation on host and
    therefore run in the fused segment's host `prepare` instead. threshold
    is exact only when its scalars are f32-representable: the host compares
    in f64 (python-float promotion) and a non-representable threshold could
    split values differently than the device's f32 compare.
    """
    kind = op.get("op")
    if kind in ("crop", "flip"):
        return True
    if kind == "threshold":
        return _f32_exact(op.get("threshold")) and _f32_exact(op.get("maxVal", 255.0))
    if kind == "colorFormat":
        return op.get("format") in ("gray", "grayscale", "bgr2rgb", "rgb2bgr")
    return False


def _split_device_ops(op_list):
    """Split an op chain into (host prefix, device-exact suffix)."""
    k = len(op_list)
    while k > 0 and _op_device_exact(op_list[k - 1]):
        k -= 1
    return list(op_list[:k]), list(op_list[k:])


def _host_forced_dtype(op_list):
    """Replay the host chain's dtype transitions: the dtype the LAST
    dtype-forcing op leaves behind (None = input dtype passes through).
    threshold promotes to f64 (numpy python-float scalar promotion); the
    blurs cast to f32. The fused finalize widens the device f32 readback
    back to this dtype — exact under the _op_device_exact gates."""
    forced = None
    for op in op_list:
        kind = op.get("op")
        if kind == "threshold":
            forced = np.float64
        elif kind in ("blur", "gaussianKernel"):
            forced = np.float32
    return forced


def _apply_device_op(x, op):
    """Batched [B,H,W,C] mirror of ImageTransformer._apply_op for the
    device-exact subset."""
    kind = op["op"]
    if kind == "crop":
        return ops.crop_batch(x, op["x"], op["y"], op["height"], op["width"])
    if kind == "flip":
        return ops.flip_batch(x, op.get("flipCode", 1))
    if kind == "threshold":
        return ops.threshold_batch(x, op["threshold"], op.get("maxVal", 255.0),
                                   op.get("type", "binary"))
    if kind == "colorFormat":
        return ops.color_format_batch(x, op["format"])
    raise FusionUnsupported(f"image op {kind!r} has no device mirror")


def _image_rows_to_arrays(col, apply_host_ops=None):
    """Struct/array rows -> (array rows, origins): the unfused per-row host
    path (ImageSchema.to_array + optional host ops), shared by the fusion
    `prepare` hooks below."""
    out = np.empty(len(col), dtype=object)
    origins = np.empty(len(col), dtype=object)
    for i, row in enumerate(col):
        if row is None:
            out[i] = None
            origins[i] = ""
            continue
        img = ImageSchema.to_array(row) if ImageSchema.is_image(row) \
            else np.asarray(row)
        origins[i] = row.get("origin", "") if isinstance(row, dict) else ""
        if apply_host_ops is not None:
            img = apply_host_ops(img)
        out[i] = np.asarray(img)
    return out, origins


def _image_struct_finalize(in_col, out_col, cast_dtype=None):
    """finalize hook: readback batch -> image-struct column, carrying the
    input rows' origins forward exactly like the host path does.
    ``cast_dtype`` widens the device f32 readback to the host chain's
    forced dtype (_host_forced_dtype) — an exact widening under the
    _op_device_exact gates."""

    def finalize(outs, ctx):
        arr = np.asarray(outs[out_col])
        if cast_dtype is not None and arr.dtype != cast_dtype:
            arr = arr.astype(cast_dtype)
        origins = ctx.get(f"origins:{in_col}")
        if origins is None:
            origins = ctx.get(f"origins:{out_col}")
        col = np.empty(len(arr), dtype=object)
        for i in range(len(arr)):
            origin = origins[i] if origins is not None else ""
            col[i] = ImageSchema.make(np.asarray(arr[i]), origin or "")
        ctx[f"origins:{out_col}"] = origins if origins is not None \
            else np.array([""] * len(arr), dtype=object)
        return {out_col: col}

    return finalize


def _image_accepts(probes):
    """Runtime dtype gate for image batches: uint8/float32 rows of rank
    2/3 (f64 images would narrow lossily on the wire — host path)."""
    for p in probes.values():
        if p["dtype"] is None:
            continue
        if p["dtype"] not in (np.dtype(np.uint8), np.dtype(np.float32)):
            return False
        if p["ndim"] not in (2, 3):
            return False
    return True


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Composable image-op pipeline on an image column.

    Reference: opencv/ImageTransformer.scala:26-150 — an ordered list of OpenCV
    stages (ResizeImage/CropImage/ColorFormat/Flip/Blur/Threshold/GaussianKernel)
    applied per image. Here each op is a dict {"op": name, ...params} executed by
    the numpy kernels in ops/image.py (jit-batched resize happens downstream in
    DNNModel where shapes are uniform).
    """

    stages = Param("stages", "Ordered list of image ops", None, ptype=list)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "image")
        kwargs.setdefault("stages", [])
        super().__init__(**kwargs)

    # -- fluent op builders (mirroring the reference's .resize(...) etc.) --
    def _add(self, **op) -> "ImageTransformer":
        st = list(self.get("stages"))
        st.append(op)
        return self.set("stages", st)

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add(op="resize", height=height, width=width)

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add(op="crop", x=x, y=y, height=height, width=width)

    def color_format(self, format: str) -> "ImageTransformer":
        return self._add(op="colorFormat", format=format)

    def flip(self, flip_code: int = 1) -> "ImageTransformer":
        return self._add(op="flip", flipCode=flip_code)

    def blur(self, height: int, width: int) -> "ImageTransformer":
        return self._add(op="blur", height=height, width=width)

    def threshold(self, threshold: float, max_val: float = 255.0,
                  threshold_type: str = "binary") -> "ImageTransformer":
        return self._add(op="threshold", threshold=threshold, maxVal=max_val,
                         type=threshold_type)

    def gaussian_kernel(self, applied_width: int, sigma: float) -> "ImageTransformer":
        return self._add(op="gaussianKernel", appliedWidth=applied_width, sigma=sigma)

    # -- execution ---------------------------------------------------------
    @staticmethod
    def _apply_op(img: np.ndarray, op: Dict[str, Any]) -> np.ndarray:
        kind = op["op"]
        if kind == "resize":
            return ops.resize(img, op["height"], op["width"])
        if kind == "crop":
            return ops.crop(img, op["x"], op["y"], op["height"], op["width"])
        if kind == "colorFormat":
            return ops.color_format(img, op["format"])
        if kind == "flip":
            return ops.flip(img, op.get("flipCode", 1))
        if kind == "blur":
            return ops.box_blur(img, op["height"], op["width"])
        if kind == "threshold":
            return ops.threshold(img, op["threshold"], op.get("maxVal", 255.0),
                                 op.get("type", "binary"))
        if kind == "gaussianKernel":
            return ops.gaussian_blur(img, op["sigma"], op.get("appliedWidth"))
        raise ValueError(f"Unknown image op {kind!r}")

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        stage_list = self.get("stages")

        def fn(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                if row is None:
                    out[i] = None
                    continue
                img = ImageSchema.to_array(row) if ImageSchema.is_image(row) else np.asarray(row)
                origin = row.get("origin", "") if isinstance(row, dict) else ""
                for op in stage_list:
                    img = self._apply_op(img, op)
                out[i] = ImageSchema.make(np.asarray(img), origin)
            return out

        return df.with_column(out_col, fn)

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_or_throw("inputCol"))
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.STRUCT
        return out

    def device_fn(self, schema: Schema):
        """Fusion contract: the longest device-exact op suffix runs batched
        on device; any prefix (resize/blur — f64 host arithmetic) runs
        per-row in `prepare` through the SAME _apply_op code the unfused
        path uses, so fused == unfused bitwise either way."""
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        op_list = list(self.get("stages") or [])
        host_ops, dev_ops = _split_device_ops(op_list)
        key = ("ImageTransformer", in_col, out_col,
               tuple(tuple(sorted(op.items())) for op in op_list))

        def prepare(cols, ctx):
            def host_chain(img):
                for op in host_ops:
                    img = self._apply_op(img, op)
                return img

            rows, origins = _image_rows_to_arrays(
                cols[in_col], host_chain if host_ops else None)
            ctx[f"origins:{in_col}"] = origins
            if out_col != in_col:
                ctx[f"origins:{out_col}"] = origins
            return {in_col: rows}

        def fn(params, env):
            x = env[in_col]
            if x.ndim not in (3, 4):
                raise FusionUnsupported("image batch must be [B,H,W(,C)]")
            for op in dev_ops:
                x = _apply_device_op(x, op)
            return {out_col: x}

        return DeviceFn(
            key=key, in_cols=(in_col,), out_cols=(out_col,), fn=fn,
            prepare=prepare,
            finalize=_image_struct_finalize(in_col, out_col,
                                            _host_forced_dtype(op_list)),
            accepts=_image_accepts,
            # a host-op prefix cannot be replayed on device-resident input:
            # the planner starts a new segment here in that case
            internal_ok=not host_ops)


class ResizeImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Resize an image column (reference image/ResizeImageTransformer.scala — AWT resize)."""

    height = Param("height", "Target height", None, lambda v: v > 0, int)
    width = Param("width", "Target width", None, lambda v: v > 0, int)
    nChannels = Param("nChannels", "Force channel count (1 or 3)", None, ptype=int)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "image")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        h, w = self.get_or_throw("height"), self.get_or_throw("width")
        nch = self.get("nChannels")

        def fn(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                if row is None:
                    out[i] = None
                    continue
                img = ImageSchema.to_array(row) if ImageSchema.is_image(row) else np.asarray(row)
                img = ops.resize(img, h, w)
                if nch == 1 and (img.ndim == 3 and img.shape[2] != 1):
                    img = ops.color_format(img, "gray")
                elif nch == 3 and (img.ndim == 2 or img.shape[2] == 1):
                    img = np.repeat(img.reshape(h, w, 1), 3, axis=2)
                origin = row.get("origin", "") if isinstance(row, dict) else ""
                out[i] = ImageSchema.make(np.asarray(img), origin)
            return out

        return df.with_column(out_col, fn)

    def device_fn(self, schema: Schema):
        """Fusion contract: the resize + channel fix run per-row in
        `prepare` (the unfused host code — bilinear resize is f64 host
        arithmetic with no exact device mirror); the device body is the
        identity, which still lets this stage head a fused segment so the
        resized batch uploads ONCE for everything downstream."""
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        h, w = self.get_or_throw("height"), self.get_or_throw("width")
        nch = self.get("nChannels")
        key = ("ResizeImageTransformer", in_col, out_col, h, w, nch)

        def host_resize(img):
            img = ops.resize(img, h, w)
            if nch == 1 and (img.ndim == 3 and img.shape[2] != 1):
                img = ops.color_format(img, "gray")
            elif nch == 3 and (img.ndim == 2 or img.shape[2] == 1):
                img = np.repeat(img.reshape(h, w, 1), 3, axis=2)
            return img

        def prepare(cols, ctx):
            rows, origins = _image_rows_to_arrays(cols[in_col], host_resize)
            ctx[f"origins:{in_col}"] = origins
            if out_col != in_col:
                ctx[f"origins:{out_col}"] = origins
            return {in_col: rows}

        def fn(params, env):
            return {out_col: env[in_col]}

        return DeviceFn(
            key=key, in_cols=(in_col,), out_cols=(out_col,), fn=fn,
            prepare=prepare, finalize=_image_struct_finalize(in_col, out_col),
            accepts=_image_accepts, internal_ok=False)


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """Image struct column -> flat CHW float vector column
    (reference image/UnrollImage.scala:28-53)."""

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "unrolled")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")

        def fn(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                if row is None:
                    out[i] = None
                    continue
                img = ImageSchema.to_array(row) if ImageSchema.is_image(row) else np.asarray(row)
                out[i] = ops.unroll_chw(img)
            return out

        return df.with_column(out_col, fn)

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_or_throw("inputCol"))
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        return out


class UnrollBinaryImage(Transformer, HasInputCol, HasOutputCol):
    """Binary (encoded bytes) column -> decode -> optional resize -> flat CHW vector
    (reference image/UnrollImage.scala UnrollBinaryImage)."""

    height = Param("height", "Resize height (optional)", None, ptype=int)
    width = Param("width", "Resize width (optional)", None, ptype=int)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "value")
        kwargs.setdefault("outputCol", "unrolled")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        h, w = self.get("height"), self.get("width")

        def fn(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, raw in enumerate(col):
                if raw is None:
                    out[i] = None
                    continue
                img = ops.decode_image(bytes(raw)) if isinstance(raw, (bytes, bytearray)) \
                    else np.asarray(raw)
                if img is None:
                    out[i] = None
                    continue
                if h is not None and w is not None:
                    img = ops.resize(img, h, w)
                out[i] = ops.unroll_chw(img)
            return out

        return df.with_column(out_col, fn)


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Dataset augmentation by flips (reference image/ImageSetAugmenter.scala):
    emits the original rows plus one extra copy per enabled flip."""

    flipLeftRight = Param("flipLeftRight", "Add horizontally-flipped copies", True, ptype=bool)
    flipUpDown = Param("flipUpDown", "Add vertically-flipped copies", False, ptype=bool)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "image")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        dfs = [df.with_column(out_col, lambda p: p[in_col])]

        def flipper(code):
            def fn(part):
                col = part[in_col]
                out = np.empty(len(col), dtype=object)
                for i, row in enumerate(col):
                    if row is None:
                        out[i] = None
                        continue
                    img = (ImageSchema.to_array(row)
                           if ImageSchema.is_image(row) else np.asarray(row))
                    origin = row.get("origin", "") if isinstance(row, dict) else ""
                    out[i] = ImageSchema.make(ops.flip(img, code), origin)
                return out
            return fn

        if self.get("flipLeftRight"):
            dfs.append(df.with_column(out_col, flipper(1)))
        if self.get("flipUpDown"):
            dfs.append(df.with_column(out_col, flipper(0)))
        result = dfs[0]
        for d in dfs[1:]:
            result = result.union(d.select(*result.columns))
        return result
