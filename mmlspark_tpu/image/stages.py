"""Image pipeline stages (ImageTransformer / Resize / Unroll / Augment parity).

All stages read/write ImageSchema struct columns (core/schema.py) — per-row dicts of
{origin, height, width, nChannels, mode, data} with an HWC numpy array payload.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import ColType, ImageSchema, Schema
from ..ops import image as ops


class ImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Composable image-op pipeline on an image column.

    Reference: opencv/ImageTransformer.scala:26-150 — an ordered list of OpenCV
    stages (ResizeImage/CropImage/ColorFormat/Flip/Blur/Threshold/GaussianKernel)
    applied per image. Here each op is a dict {"op": name, ...params} executed by
    the numpy kernels in ops/image.py (jit-batched resize happens downstream in
    DNNModel where shapes are uniform).
    """

    stages = Param("stages", "Ordered list of image ops", None, ptype=list)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "image")
        kwargs.setdefault("stages", [])
        super().__init__(**kwargs)

    # -- fluent op builders (mirroring the reference's .resize(...) etc.) --
    def _add(self, **op) -> "ImageTransformer":
        st = list(self.get("stages"))
        st.append(op)
        return self.set("stages", st)

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._add(op="resize", height=height, width=width)

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._add(op="crop", x=x, y=y, height=height, width=width)

    def color_format(self, format: str) -> "ImageTransformer":
        return self._add(op="colorFormat", format=format)

    def flip(self, flip_code: int = 1) -> "ImageTransformer":
        return self._add(op="flip", flipCode=flip_code)

    def blur(self, height: int, width: int) -> "ImageTransformer":
        return self._add(op="blur", height=height, width=width)

    def threshold(self, threshold: float, max_val: float = 255.0,
                  threshold_type: str = "binary") -> "ImageTransformer":
        return self._add(op="threshold", threshold=threshold, maxVal=max_val,
                         type=threshold_type)

    def gaussian_kernel(self, applied_width: int, sigma: float) -> "ImageTransformer":
        return self._add(op="gaussianKernel", appliedWidth=applied_width, sigma=sigma)

    # -- execution ---------------------------------------------------------
    @staticmethod
    def _apply_op(img: np.ndarray, op: Dict[str, Any]) -> np.ndarray:
        kind = op["op"]
        if kind == "resize":
            return ops.resize(img, op["height"], op["width"])
        if kind == "crop":
            return ops.crop(img, op["x"], op["y"], op["height"], op["width"])
        if kind == "colorFormat":
            return ops.color_format(img, op["format"])
        if kind == "flip":
            return ops.flip(img, op.get("flipCode", 1))
        if kind == "blur":
            return ops.box_blur(img, op["height"], op["width"])
        if kind == "threshold":
            return ops.threshold(img, op["threshold"], op.get("maxVal", 255.0),
                                 op.get("type", "binary"))
        if kind == "gaussianKernel":
            return ops.gaussian_blur(img, op["sigma"], op.get("appliedWidth"))
        raise ValueError(f"Unknown image op {kind!r}")

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        stage_list = self.get("stages")

        def fn(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                if row is None:
                    out[i] = None
                    continue
                img = ImageSchema.to_array(row) if ImageSchema.is_image(row) else np.asarray(row)
                origin = row.get("origin", "") if isinstance(row, dict) else ""
                for op in stage_list:
                    img = self._apply_op(img, op)
                out[i] = ImageSchema.make(np.asarray(img), origin)
            return out

        return df.with_column(out_col, fn)

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_or_throw("inputCol"))
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.STRUCT
        return out


class ResizeImageTransformer(Transformer, HasInputCol, HasOutputCol):
    """Resize an image column (reference image/ResizeImageTransformer.scala — AWT resize)."""

    height = Param("height", "Target height", None, lambda v: v > 0, int)
    width = Param("width", "Target width", None, lambda v: v > 0, int)
    nChannels = Param("nChannels", "Force channel count (1 or 3)", None, ptype=int)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "image")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        h, w = self.get_or_throw("height"), self.get_or_throw("width")
        nch = self.get("nChannels")

        def fn(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                if row is None:
                    out[i] = None
                    continue
                img = ImageSchema.to_array(row) if ImageSchema.is_image(row) else np.asarray(row)
                img = ops.resize(img, h, w)
                if nch == 1 and (img.ndim == 3 and img.shape[2] != 1):
                    img = ops.color_format(img, "gray")
                elif nch == 3 and (img.ndim == 2 or img.shape[2] == 1):
                    img = np.repeat(img.reshape(h, w, 1), 3, axis=2)
                origin = row.get("origin", "") if isinstance(row, dict) else ""
                out[i] = ImageSchema.make(np.asarray(img), origin)
            return out

        return df.with_column(out_col, fn)


class UnrollImage(Transformer, HasInputCol, HasOutputCol):
    """Image struct column -> flat CHW float vector column
    (reference image/UnrollImage.scala:28-53)."""

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "unrolled")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")

        def fn(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                if row is None:
                    out[i] = None
                    continue
                img = ImageSchema.to_array(row) if ImageSchema.is_image(row) else np.asarray(row)
                out[i] = ops.unroll_chw(img)
            return out

        return df.with_column(out_col, fn)

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_or_throw("inputCol"))
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        return out


class UnrollBinaryImage(Transformer, HasInputCol, HasOutputCol):
    """Binary (encoded bytes) column -> decode -> optional resize -> flat CHW vector
    (reference image/UnrollImage.scala UnrollBinaryImage)."""

    height = Param("height", "Resize height (optional)", None, ptype=int)
    width = Param("width", "Resize width (optional)", None, ptype=int)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "value")
        kwargs.setdefault("outputCol", "unrolled")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        h, w = self.get("height"), self.get("width")

        def fn(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, raw in enumerate(col):
                if raw is None:
                    out[i] = None
                    continue
                img = ops.decode_image(bytes(raw)) if isinstance(raw, (bytes, bytearray)) \
                    else np.asarray(raw)
                if img is None:
                    out[i] = None
                    continue
                if h is not None and w is not None:
                    img = ops.resize(img, h, w)
                out[i] = ops.unroll_chw(img)
            return out

        return df.with_column(out_col, fn)


class ImageSetAugmenter(Transformer, HasInputCol, HasOutputCol):
    """Dataset augmentation by flips (reference image/ImageSetAugmenter.scala):
    emits the original rows plus one extra copy per enabled flip."""

    flipLeftRight = Param("flipLeftRight", "Add horizontally-flipped copies", True, ptype=bool)
    flipUpDown = Param("flipUpDown", "Add vertically-flipped copies", False, ptype=bool)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "image")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        dfs = [df.with_column(out_col, lambda p: p[in_col])]

        def flipper(code):
            def fn(part):
                col = part[in_col]
                out = np.empty(len(col), dtype=object)
                for i, row in enumerate(col):
                    if row is None:
                        out[i] = None
                        continue
                    img = (ImageSchema.to_array(row)
                           if ImageSchema.is_image(row) else np.asarray(row))
                    origin = row.get("origin", "") if isinstance(row, dict) else ""
                    out[i] = ImageSchema.make(ops.flip(img, code), origin)
                return out
            return fn

        if self.get("flipLeftRight"):
            dfs.append(df.with_column(out_col, flipper(1)))
        if self.get("flipUpDown"):
            dfs.append(df.with_column(out_col, flipper(0)))
        result = dfs[0]
        for d in dfs[1:]:
            result = result.union(d.select(*result.columns))
        return result
