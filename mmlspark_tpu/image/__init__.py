"""Image pipeline stages: transform, resize, unroll, augment, featurize.

Parity targets: opencv/ImageTransformer.scala, image/ResizeImageTransformer.scala,
image/UnrollImage.scala, image/ImageSetAugmenter.scala, image/ImageFeaturizer.scala.
"""

from .stages import (
    ImageSetAugmenter,
    ImageTransformer,
    ResizeImageTransformer,
    UnrollBinaryImage,
    UnrollImage,
)
from .featurizer import ImageFeaturizer

__all__ = [
    "ImageFeaturizer", "ImageSetAugmenter", "ImageTransformer",
    "ResizeImageTransformer", "UnrollBinaryImage", "UnrollImage",
]
