"""ImageFeaturizer — headless-CNN transfer learning / featurization.

Reference: image/ImageFeaturizer.scala:133-178 — pick an output node by cutting
``cutOutputLayers`` layers off the head (via the model schema's ``layerNames``),
auto-resize inputs to the model's required size, unroll, delegate to CNTKModel.

TPU redesign: the FunctionModel's ``layer_names`` (head-first) provide the cut
points; resize happens host-side per image, then DNNModel runs the jitted batched
forward fetching the tapped activation directly — no unroll/re-roll round trip
through flat vectors (the CHW unroll existed only because CNTK consumed flat
buffers; XLA consumes [B,H,W,C] natively).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import ColType, ImageSchema, Schema
from ..models.dnn_model import DNNModel
from ..models.module import FunctionModel
from ..ops import image as ops


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    """Featurize images (or encoded-image bytes) through a headless CNN."""

    model = ComplexParam("model", "The FunctionModel backbone")
    cutOutputLayers = Param("cutOutputLayers",
                            "How many layers to cut off the head (1 = pooled features)",
                            1, lambda v: v >= 0, int)
    dropNa = Param("dropNa", "Drop rows whose image failed to decode", True, ptype=bool)
    batchSize = Param("batchSize", "Eval minibatch size", 64, lambda v: v > 0, int)
    scaleFactor = Param("scaleFactor", "Multiply pixel values (1/255 to normalize)",
                        1.0, ptype=float)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)
        self._dnn_cache = None  # (key, DNNModel) — keeps jit cache warm across calls

    def set_model(self, model: FunctionModel) -> "ImageFeaturizer":
        return self.set("model", model)

    def set_cut_output_layers(self, n: int) -> "ImageFeaturizer":
        return self.set("cutOutputLayers", n)

    def _output_node(self, model: FunctionModel) -> Optional[str]:
        cut = self.get("cutOutputLayers")
        if cut == 0:
            return None  # full head output
        if cut >= len(model.layer_names):
            raise ValueError(
                f"cutOutputLayers={cut} but model has {len(model.layer_names)} cut points")
        return model.layer_names[cut]

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        model: FunctionModel = self.get_or_throw("model")
        fmt = getattr(model, "data_format", "NHWC")
        if fmt == "NCHW":  # imported ONNX backbones
            c, h, w = model.input_shape
        else:
            h, w, c = model.input_shape
        scale = self.get("scaleFactor")

        # 1. normalize input rows to fixed-shape HWC float32 arrays (auto-resize,
        #    reference ImageFeaturizer.scala:141-165)
        def prep(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                img = None
                if row is None:
                    pass
                elif isinstance(row, (bytes, bytearray)):
                    img = ops.decode_image(bytes(row))
                elif ImageSchema.is_image(row):
                    img = ImageSchema.to_array(row)
                else:
                    img = np.asarray(row)
                    if img.ndim == 1:  # unrolled CHW vector
                        img = np.moveaxis(img.reshape(c, h, w), 0, -1)
                if img is None:
                    out[i] = None
                    continue
                img = ops.resize(img, h, w)
                if img.ndim == 2:
                    img = img[:, :, None]
                if img.shape[2] != c:
                    img = (np.repeat(img[:, :, :1], c, axis=2) if img.shape[2] < c
                           else img[:, :, :c])
                img = img.astype(np.float32) * np.float32(scale)
                out[i] = np.ascontiguousarray(img.transpose(2, 0, 1)) \
                    if fmt == "NCHW" else img
            return out

        prepped = df.with_column("__dnn_input__", prep)
        if self.get("dropNa"):
            prepped = prepped.dropna(subset=["__dnn_input__"])

        node = self._output_node(model)
        key = (id(model), node, out_col, self.get("batchSize"))
        if self._dnn_cache is None or self._dnn_cache[0] != key:
            dnn = DNNModel(inputCol="__dnn_input__", outputCol=out_col,
                           batchSize=self.get("batchSize"))
            dnn.set_model(model)
            if node is not None:
                dnn.set_output_node(node)
            self._dnn_cache = (key, dnn)
        dnn = self._dnn_cache[1]
        return dnn.transform(prepped).drop("__dnn_input__")

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_or_throw("inputCol"))
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        return out
