"""ImageFeaturizer — headless-CNN transfer learning / featurization.

Reference: image/ImageFeaturizer.scala:133-178 — pick an output node by cutting
``cutOutputLayers`` layers off the head (via the model schema's ``layerNames``),
auto-resize inputs to the model's required size, unroll, delegate to CNTKModel.

TPU redesign: the FunctionModel's ``layer_names`` (head-first) provide the cut
points; resize happens host-side per image, then DNNModel runs the jitted batched
forward fetching the tapped activation directly — no unroll/re-roll round trip
through flat vectors (the CHW unroll existed only because CNTK consumed flat
buffers; XLA consumes [B,H,W,C] natively).

Wire format: batches ship to the device **uint8** (the decoded pixel dtype)
by default; the ``scaleFactor`` multiply, float cast, and any NCHW layout
transpose are fused into the compiled forward via a PreprocessSpec
(parallel/ingest.py) — 4x fewer host->device bytes than the old host-side
``astype(float32) * scale`` with identical numerics (uint8 -> f32 cast and
an f32 multiply are exact). ``hostPreprocess=True`` restores the legacy
float32-wire host path.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.device_stage import DeviceFn, FusionUnsupported
from ..core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import ColType, ImageSchema, Schema
from ..models.dnn_model import DNNModel
from ..models.module import FunctionModel
from ..ops import image as ops
from ..parallel.ingest import PreprocessSpec


class ImageFeaturizer(Transformer, HasInputCol, HasOutputCol):
    """Featurize images (or encoded-image bytes) through a headless CNN.

    Batches ride the host->device link in their decoded dtype (uint8 by
    default — the uint8-wire default); ``scaleFactor`` scaling and NCHW
    layout transposes run inside the compiled forward (see PreprocessSpec).
    """

    model = ComplexParam("model", "The FunctionModel backbone")
    cutOutputLayers = Param("cutOutputLayers",
                            "How many layers to cut off the head (1 = pooled features)",
                            1, lambda v: v >= 0, int)
    dropNa = Param("dropNa", "Drop rows whose image failed to decode", True, ptype=bool)
    batchSize = Param("batchSize", "Eval minibatch size", 64, lambda v: v > 0, int)
    scaleFactor = Param("scaleFactor", "Multiply pixel values (1/255 to normalize)",
                        1.0, ptype=float)
    hostPreprocess = Param(
        "hostPreprocess",
        "Do the float cast / scale / layout transpose on the HOST per image "
        "(the legacy float32 wire format, 4x the H2D bytes). Default False: "
        "pixels stay uint8 on the wire and preprocessing fuses into the "
        "compiled forward.", False, ptype=bool)
    ringDepth = Param("ringDepth",
                      "In-flight batches in the DNN transfer ring", 2,
                      lambda v: v > 0, int)

    def __init__(self, **kwargs):
        kwargs.setdefault("inputCol", "image")
        kwargs.setdefault("outputCol", "features")
        super().__init__(**kwargs)
        self._dnn_cache = None  # (key, DNNModel) — keeps jit cache warm across calls

    def set_model(self, model: FunctionModel) -> "ImageFeaturizer":
        return self.set("model", model)

    def set_cut_output_layers(self, n: int) -> "ImageFeaturizer":
        return self.set("cutOutputLayers", n)

    def _output_node(self, model: FunctionModel) -> Optional[str]:
        cut = self.get("cutOutputLayers")
        if cut == 0:
            return None  # full head output
        if cut >= len(model.layer_names):
            raise ValueError(
                f"cutOutputLayers={cut} but model has {len(model.layer_names)} cut points")
        return model.layer_names[cut]

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        model: FunctionModel = self.get_or_throw("model")
        fmt = getattr(model, "data_format", "NHWC")
        if fmt == "NCHW":  # imported ONNX backbones
            c, h, w = model.input_shape
        else:
            h, w, c = model.input_shape
        scale = self.get("scaleFactor")
        host_pre = self.get("hostPreprocess")
        # device-side preprocess: float cast + scale on device, plus the
        # HWC -> CHW layout move for ONNX backbones; the wire keeps the
        # decoded dtype (uint8 images: 4x fewer H2D bytes)
        spec = PreprocessSpec(scale=scale,
                              transpose=(2, 0, 1) if fmt == "NCHW" else None)

        # 1. normalize input rows to fixed-shape HWC arrays (auto-resize,
        #    reference ImageFeaturizer.scala:141-165); dtype is preserved
        #    (wire dtype) unless hostPreprocess is set
        def prep(part):
            col = part[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                img = None
                if row is None:
                    pass
                elif isinstance(row, (bytes, bytearray)):
                    img = ops.decode_image(bytes(row))
                elif ImageSchema.is_image(row):
                    img = ImageSchema.to_array(row)
                else:
                    img = np.asarray(row)
                    if img.ndim == 1:  # unrolled CHW vector
                        img = np.moveaxis(img.reshape(c, h, w), 0, -1)
                if img is None:
                    out[i] = None
                    continue
                img = ops.resize(img, h, w)
                if img.ndim == 2:
                    img = img[:, :, None]
                if img.shape[2] != c:
                    img = (np.repeat(img[:, :, :1], c, axis=2) if img.shape[2] < c
                           else img[:, :, :c])
                out[i] = spec.apply_host_row(img) if host_pre \
                    else np.ascontiguousarray(img)
            return out

        prepped = df.with_column("__dnn_input__", prep)
        if self.get("dropNa"):
            prepped = prepped.dropna(subset=["__dnn_input__"])

        node = self._output_node(model)
        key = (id(model), node, out_col, self.get("batchSize"),
               None if host_pre else spec, self.get("ringDepth"))
        if self._dnn_cache is None or self._dnn_cache[0] != key:
            dnn = DNNModel(inputCol="__dnn_input__", outputCol=out_col,
                           batchSize=self.get("batchSize"),
                           ringDepth=self.get("ringDepth"))
            dnn.set_model(model)
            if not host_pre:
                dnn.set_preprocess(spec)
            if node is not None:
                dnn.set_output_node(node)
            self._dnn_cache = (key, dnn)
        dnn = self._dnn_cache[1]
        return dnn.transform(prepped).drop("__dnn_input__")

    @property
    def last_ingest_stats(self):
        """Ingest decomposition of the most recent transform (delegates to
        the wrapped DNNModel) — None before the first transform."""
        return self._dnn_cache[1].last_ingest_stats if self._dnn_cache else None

    def device_fn(self, schema: Schema):
        """Fusion contract: decode/resize/channel-fix run per-row in
        `prepare` (the unfused host prep); the device body is the channel
        fix mirror + PreprocessSpec + ONE forward to the tapped activation.
        Upstream in-segment image stages feed it device-resident batches —
        trace-time shape gates fall back when (H, W) does not match the
        backbone."""
        model: Optional[FunctionModel] = self.get("model")
        if model is None:
            return None
        from ..parallel.mesh import DATA_AXIS, MeshContext

        mesh = MeshContext.current()
        if mesh is not None and mesh.shape.get(DATA_AXIS, 1) > 1:
            return None  # mesh-sharded eval keeps the unfused path
        fmt = getattr(model, "data_format", "NHWC")
        if fmt == "NCHW":
            c, h, w = model.input_shape
        else:
            h, w, c = model.input_shape
        spec = PreprocessSpec(scale=self.get("scaleFactor"),
                              transpose=(2, 0, 1) if fmt == "NCHW" else None)
        node = self._output_node(model)
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        # cache_token (not id): the shared CompileCache key must survive a
        # process restart for the fleet's persistent tier to hit
        key = ("ImageFeaturizer", in_col, out_col, model.cache_token(),
               node, spec.cache_key(), h, w, c)

        def prepare(cols, ctx):
            # the unfused per-row prep (decode -> resize -> channel fix);
            # the spec runs on DEVICE in both hostPreprocess modes — its ops
            # are exact, so the wire stays the decoded dtype
            col = cols[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                img = None
                if row is None:
                    pass
                elif isinstance(row, (bytes, bytearray)):
                    img = ops.decode_image(bytes(row))
                elif ImageSchema.is_image(row):
                    img = ImageSchema.to_array(row)
                else:
                    img = np.asarray(row)
                    if img.ndim == 1:
                        img = np.moveaxis(img.reshape(c, h, w), 0, -1)
                if img is None:
                    out[i] = None
                    continue
                img = ops.resize(img, h, w)
                if img.ndim == 2:
                    img = img[:, :, None]
                if img.shape[2] != c:
                    img = (np.repeat(img[:, :, :1], c, axis=2)
                           if img.shape[2] < c else img[:, :, :c])
                out[i] = np.ascontiguousarray(img)
            return {in_col: out}

        def accepts(probes):
            p = probes.get(in_col)
            if p is None or p["dtype"] is None:
                return True
            return p["dtype"].kind in "fuib" and p["ndim"] in (2, 3)

        def fn(params, env):
            import jax.numpy as jnp

            x = env[in_col]
            if x.ndim == 3:
                x = x[:, :, :, None]
            if x.ndim != 4:
                raise FusionUnsupported("image batch must be [B,H,W,C]")
            if (x.shape[1], x.shape[2]) != (h, w):
                raise FusionUnsupported(
                    f"input {x.shape[1]}x{x.shape[2]} != backbone {h}x{w}; "
                    f"resize upstream (host prep only runs at segment heads)")
            x = ops.fix_channels_batch(x, c)
            y = spec.apply_device(x)
            live = FunctionModel(model.module, params, model.input_shape,
                                 model.layer_names, model.name)
            act = live.apply_taps(y, [node])[node]
            # f32 on device == the unfused host-side np.asarray(y, float32)
            return {out_col: act.astype(jnp.float32)}

        return DeviceFn(
            key=key, in_cols=(in_col,), out_cols=(out_col,), fn=fn,
            params=model.params, prepare=prepare, accepts=accepts,
            reject_sparse=False, drop_invalid=bool(self.get("dropNa")),
            heavy=True)

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_or_throw("inputCol"))
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        return out
