"""Model layer: functional NN modules, flagship architectures, DNN inference stage."""

from .module import (
    BatchNorm,
    Conv2D,
    Dense,
    Fn,
    FunctionModel,
    GlobalAvgPool,
    MaxPool,
    Module,
    Residual,
    Sequential,
    flatten,
    matmul_dtype,
    matmul_precision,
    relu,
)
from .resnet import build_resnet, param_shardings, resnet, resnet18, resnet50
from .dnn_model import DNNModel
from .graph_module import GraphModule, GraphNode
from .torch_import import from_torch_resnet

__all__ = [
    "BatchNorm", "Conv2D", "DNNModel", "Dense", "Fn", "FunctionModel",
    "GlobalAvgPool", "GraphModule", "GraphNode", "MaxPool", "Module", "Residual",
    "Sequential", "build_resnet", "flatten", "from_torch_resnet", "param_shardings",
    "relu", "resnet", "resnet18", "resnet50",
]
