"""Model layer: functional NN modules, flagship architectures, DNN inference stage."""

from .module import (
    BatchNorm,
    Conv2D,
    Dense,
    Fn,
    FunctionModel,
    GlobalAvgPool,
    MaxPool,
    Module,
    Residual,
    Sequential,
    flatten,
    matmul_dtype,
    matmul_precision,
    relu,
)
from .attention import (
    BiLSTM,
    Embed,
    LSTM,
    LayerNorm,
    MultiHeadAttention,
    bilstm_tagger,
    dense_attention,
    ring_attention,
    transformer_block,
    transformer_encoder,
)
from .moe import MoE, expert_shardings
from .resnet import build_resnet, param_shardings, resnet, resnet18, resnet50
from .dnn_model import DNNModel
from .graph_module import GraphModule, GraphNode
from .torch_import import from_torch_resnet

__all__ = [
    "BatchNorm", "BiLSTM", "Conv2D", "DNNModel", "Dense", "Embed", "Fn",
    "FunctionModel", "GlobalAvgPool", "GraphModule", "GraphNode", "LSTM",
    "LayerNorm", "MaxPool", "MoE", "Module", "MultiHeadAttention", "Residual",
    "Sequential", "bilstm_tagger", "build_resnet", "dense_attention",
    "expert_shardings", "flatten", "from_torch_resnet", "param_shardings",
    "relu", "resnet", "resnet18", "resnet50", "ring_attention",
    "transformer_block", "transformer_encoder",
]
