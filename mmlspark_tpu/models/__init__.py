"""Model layer: functional NN modules, flagship architectures, DNN inference stage."""

from .module import (
    BatchNorm,
    Conv2D,
    Dense,
    Fn,
    FunctionModel,
    GlobalAvgPool,
    MaxPool,
    Module,
    Residual,
    Sequential,
    flatten,
    relu,
)
from .resnet import build_resnet, param_shardings, resnet, resnet18, resnet50
from .dnn_model import DNNModel

__all__ = [
    "BatchNorm", "Conv2D", "DNNModel", "Dense", "Fn", "FunctionModel",
    "GlobalAvgPool", "MaxPool", "Module", "Residual", "Sequential",
    "build_resnet", "flatten", "param_shardings", "relu", "resnet",
    "resnet18", "resnet50",
]
