"""Train-state checkpointing: params + optimizer state + step.

Reference scope (SURVEY §5 checkpoint/resume): the reference persists models,
not training step state — its continued-training hooks are model-level (VW
initialModel bytes, LightGBM BoosterMerge). A TPU training loop additionally
needs step-level resume: params, optimizer state, and the step counter
restored onto the right device shardings. Orbax (the standard JAX checkpoint
library) handles the array serialization; restore takes a reference state so
sharded trees come back with their original NamedShardings.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from ..parallel.mesh import fetch_global
from .training import TrainState


def save_train_state(state: TrainState, path: str) -> None:
    """Write params + opt_state + step under ``path`` (overwrites).

    Orbax handles sharded global arrays natively (each process writes its
    shards); the step counter is fetched via fetch_global because a bare
    np.asarray of a replicated scalar raises under a multi-process mesh.
    Collective when multi-process: call from every process."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckpt = ocp.PyTreeCheckpointer()
    tree = {"params": state.params, "opt_state": state.opt_state,
            "step": np.asarray(fetch_global(state.step))}
    # block: callers treat save as durable once it returns
    ckpt.save(path, tree, force=True)


def load_train_state(path: str, like: Optional[TrainState] = None) -> TrainState:
    """Restore a TrainState.

    ``like``: a reference state (e.g. fresh init_train_state(...)) providing
    the tree structure and target shardings — required to restore optax state
    (whose pytree types aren't stored) and to place arrays back on a mesh.
    Without it, arrays come back host-resident with plain structure.
    """
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckpt = ocp.PyTreeCheckpointer()
    if like is None:
        tree = ckpt.restore(path)
        return TrainState(tree["params"], tree["opt_state"],
                          np.asarray(tree["step"]))

    ref = {"params": like.params, "opt_state": like.opt_state,
           "step": np.asarray(like.step)}
    restore_args = jax.tree.map(
        lambda leaf: ocp.ArrayRestoreArgs(sharding=leaf.sharding)
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding")
        else ocp.RestoreArgs(),
        ref)
    tree = ckpt.restore(
        path, args=ocp.args.PyTreeRestore(
            item=ref, restore_args=restore_args))
    return TrainState(tree["params"], tree["opt_state"],
                      np.asarray(tree["step"]))
