"""Sequence models: attention (dense + ring), transformer encoder, (Bi)LSTM.

The reference's sequence story is CNTK BiLSTM inference (notebooks
"DeepLearning - BiLSTM Medical Entity Extraction"; the CNTK model is loaded
through the generic evaluator, CNTK/SerializableFunction.scala:23-143). The
TPU-first redesign makes sequence modeling a native model family on the
module tree — addressable layers, taps, DNNModel/ImageFeaturizer machinery —
and makes LONG sequences first-class:

  - ``ring_attention``: blockwise attention with the KV shards rotating
    around the ``seq`` mesh axis via ``ppermute`` (one ICI hop per step)
    and a streaming, numerically-stable softmax (flash-style running
    max/denominator). Peak memory per chip is O(T_local^2) instead of
    O(T^2); the sequence scales with the number of chips.
  - ``MultiHeadAttention(ring_axis="seq")``: the same module runs dense
    single-chip or ring-parallel under ``shard_map`` — the module code does
    not change, only the mesh placement does (scaling-book style: annotate,
    let XLA/collectives do the rest).
  - ``LSTM``/``BiLSTM``: ``lax.scan`` over time (static shapes, no Python
    loops under jit), concat of forward/backward passes.

All modules follow module.py conventions: shapes exclude the batch dim,
``init -> (params, out_shape)``, bf16 matmuls via matmul_dtype().
"""

from __future__ import annotations

import math
import os
from typing import Optional, Tuple

import numpy as np

from .module import Fn, Module, Sequential, _rng_split, matmul_dtype


# ---------------------------------------------------------------------------
# functional attention kernels
# ---------------------------------------------------------------------------

def _flash_dispatch(q, k, v, causal, q_offset, k_offset):
    """Route to the Pallas TPU flash-attention kernel when it applies.

    Dispatch conditions: TPU backend, bf16 inputs (the kernel's MXU passes
    round like bf16, so the f32 path keeps the exact XLA lowering for
    matmul_precision('float32') equivalence tests), no shard offsets,
    full-square causal only, seq lens divisible by the kernel's 128 block,
    head dim 64 or a multiple of 128 (lane width). Returns None to fall back.
    ``MMLSPARK_TPU_NO_FLASH=1`` forces the XLA path.

    Measured on v5e (BENCH_seq.json, min-of-3 on-device loops): speedup over
    the XLA lowering grows with length — 0.98x @T1024, 1.09x @2048,
    1.15x @4096, 1.28x @8192 — so dispatch requires
    T >= MMLSPARK_TPU_FLASH_MIN_T (default 1024; XLA's attention is already
    streaming-quality below that). The decisive win is MEMORY: the XLA path
    fails to compile at B=2,H=8,T=16384 (the f32 score tensor alone is
    ~17 GB) while the flash kernel streams K/V blocks through VMEM and runs
    fine — ~4x longer single-chip context, multiplying with ring attention's
    per-chip scaling.
    """
    if os.environ.get("MMLSPARK_TPU_NO_FLASH", "") not in ("", "0"):
        return None
    import jax
    import jax.numpy as jnp

    if q.dtype != jnp.bfloat16:
        return None
    try:
        if jax.default_backend() != "tpu":
            return None
    except Exception:
        return None
    if q_offset or k_offset:
        return None
    _, tq, _, d = q.shape
    tk = k.shape[1]
    if causal and tq != tk:
        return None
    if tq % 128 or tk % 128 or (d != 64 and d % 128):
        return None
    min_t = int(os.environ.get("MMLSPARK_TPU_FLASH_MIN_T", "1024"))
    if tk < min_t:
        return None
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention)

    o = flash_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal, sm_scale=1.0 / math.sqrt(d))
    return o.transpose(0, 2, 1, 3).astype(v.dtype)


def dense_attention(q, k, v, causal: bool = False,
                    q_offset: int = 0, k_offset: int = 0):
    """Reference attention. q:[B,Tq,H,D] k/v:[B,Tk,H,D] -> [B,Tq,H,D].
    ``*_offset`` are global position offsets for causal masking of shards.
    On TPU with bf16 inputs the inner computation dispatches to the Pallas
    flash-attention kernel (see _flash_dispatch)."""
    import jax.numpy as jnp

    flash = _flash_dispatch(q, k, v, causal, q_offset, k_offset)
    if flash is not None:
        return flash

    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, v.dtype.type(scale) * k,
                   preferred_element_type=jnp.float32)
    if causal:
        qpos = jnp.arange(q.shape[1]) + q_offset
        kpos = jnp.arange(k.shape[1]) + k_offset
        s = jnp.where(kpos[None, :] > qpos[:, None], -jnp.inf, s)
    # rows with no valid key (a query shard strictly before every key in the
    # block) must yield zeros, not NaN from exp(-inf - -inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(jnp.where(jnp.isfinite(s), s - safe_m, -jnp.inf))
    denom = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(denom == 0.0, 1.0, denom)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(v.dtype)


def ring_attention(q, k, v, axis_name: str, axis_size: int,
                   causal: bool = False):
    """Sequence-parallel attention inside ``shard_map``: every chip holds a
    [B, T_local, H, D] shard of q/k/v along ``axis_name``; KV blocks rotate
    around the ring (ppermute) while each chip accumulates its queries'
    output with a streaming softmax (running max ``m``, denominator ``l``).

    Design: the scaling-book recipe for context parallelism — compute rides
    the MXU on [T_local, T_local] blocks, comms ride ICI one neighbor hop per
    step, overlap comes from XLA pipelining the permute with the block
    matmul. Equivalent to dense attention over the gathered sequence to
    ~1e-5 (test_attention.py proves it on an 8-device mesh).
    """
    import jax
    import jax.numpy as jnp

    B, T, H, D = q.shape
    my = jax.lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32)

    o = jnp.zeros((B, T, H, D), dtype=jnp.float32)
    m = jnp.full((B, H, T), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((B, H, T), dtype=jnp.float32)
    # mark the fresh accumulators as device-varying over the ring axis
    # (shard_map's vma typing requires scan carries in == carries out;
    # jax < 0.5 has neither pcast nor pvary and no vma typing to satisfy)
    _vary = getattr(jax.lax, "pcast", None)
    if _vary is not None:
        o, m, l = (_vary(a, (axis_name,), to="varying") for a in (o, m, l))
    elif hasattr(jax.lax, "pvary"):
        # analysis: allow J001 -- hasattr-guarded on the line above: this IS the gate
        o, m, l = (jax.lax.pvary(a, (axis_name,)) for a in (o, m, l))

    def block(carry, step):
        o, m, l, kb, vb = carry
        kv_idx = (my - step) % axis_size  # whose KV shard we hold this step
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kb.astype(jnp.float32)) * scale
        if causal:
            qpos = jnp.arange(T) + my * T
            kpos = jnp.arange(T) + kv_idx * T
            s = jnp.where(kpos[None, :] > qpos[:, None], -jnp.inf, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # exp(-inf - -inf) guards: rows with no valid keys yet stay zeroed
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(jnp.where(jnp.isfinite(s), s - safe_m[..., None], -jnp.inf))
        p = jnp.where(jnp.isfinite(p), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vb.astype(jnp.float32))
        o = o * corr.transpose(0, 2, 1)[..., None] + pv
        # rotate KV to the next neighbor (ring over ICI)
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (o, m_new, l, kb, vb), None

    (o, m, l, _, _), _ = jax.lax.scan(
        block, (o, m, l, k, v), jnp.arange(axis_size))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (causal edge) -> 0 out
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# modules
# ---------------------------------------------------------------------------

class LayerNorm(Module):
    """LayerNorm over the last dim (f32 statistics, dtype-preserving)."""

    def __init__(self, eps: float = 1e-5):
        self.eps = eps

    def init(self, rng, in_shape):
        d = in_shape[-1]
        return {"scale": np.ones((d,), np.float32),
                "bias": np.zeros((d,), np.float32)}, tuple(in_shape)

    def apply(self, params, x, train: bool = False):
        import jax
        import jax.numpy as jnp

        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + self.eps)
        return (y * params["scale"] + params["bias"]).astype(x.dtype)


class Embed(Module):
    """Token ids [T] -> embeddings [T, dim] (gather; rides HBM, not MXU)."""

    def __init__(self, vocab_size: int, dim: int):
        self.vocab_size = vocab_size
        self.dim = dim

    def init(self, rng, in_shape):
        import jax

        table = jax.random.normal(rng, (self.vocab_size, self.dim),
                                  dtype=np.float32) * 0.02
        return {"table": table}, tuple(in_shape) + (self.dim,)

    def apply(self, params, x, train: bool = False):
        import jax.numpy as jnp

        return jnp.take(jnp.asarray(params["table"]), x.astype(jnp.int32),
                        axis=0)


class MultiHeadAttention(Module):
    """Self-attention on [B, T, D]. ``ring_axis`` switches the inner kernel
    to ring_attention when applied under shard_map with that axis present
    (T then is the LOCAL shard length); dense otherwise."""

    def __init__(self, num_heads: int, causal: bool = False,
                 ring_axis: Optional[str] = None,
                 ring_axis_size: Optional[int] = None):
        self.num_heads = num_heads
        self.causal = causal
        self.ring_axis = ring_axis
        self.ring_axis_size = ring_axis_size

    def init(self, rng, in_shape):
        import jax

        t, d = in_shape
        if d % self.num_heads:
            raise ValueError(f"dim {d} not divisible by heads {self.num_heads}")
        keys = _rng_split(rng, 4)
        std = np.float32(1.0 / math.sqrt(d))
        params = {name: jax.random.normal(k, (d, d), dtype=np.float32) * std
                  for name, k in zip(("wq", "wk", "wv", "wo"), keys)}
        return params, (t, d)

    def apply(self, params, x, train: bool = False):
        import jax.numpy as jnp

        dt = getattr(jnp, matmul_dtype())
        B, T, D = x.shape
        H = self.num_heads
        xd = x.astype(dt)

        def proj(w):
            return jnp.einsum("btd,de->bte", xd, jnp.asarray(w).astype(dt),
                              preferred_element_type=jnp.float32
                              ).reshape(B, T, H, D // H).astype(dt)

        q, k, v = proj(params["wq"]), proj(params["wk"]), proj(params["wv"])
        if self.ring_axis is not None:
            if self.ring_axis_size is None:
                raise ValueError("ring_axis requires ring_axis_size "
                                 "(static ring length)")
            o = ring_attention(q, k, v, self.ring_axis, self.ring_axis_size,
                               causal=self.causal)
        else:
            o = dense_attention(q, k, v, causal=self.causal)
        o = o.reshape(B, T, D)
        out = jnp.einsum("btd,de->bte", o.astype(dt),
                         jnp.asarray(params["wo"]).astype(dt),
                         preferred_element_type=jnp.float32)
        return out.astype(jnp.float32)


def _gelu(x):
    import jax

    return jax.nn.gelu(x)


def transformer_block(dim: int, num_heads: int, mlp_ratio: int = 4,
                      causal: bool = False, ring_axis: Optional[str] = None,
                      ring_axis_size: Optional[int] = None,
                      moe_experts: Optional[int] = None,
                      moe_capacity_factor: float = 1.5) -> Sequential:
    """Pre-norm transformer block as a named Sequential (taps work).
    ``moe_experts``: replace the dense FFN with a switch-MoE of that many
    experts (shard their weights over the ``expert`` axis via
    ``moe.expert_shardings`` for expert parallelism)."""
    from .module import Dense, Residual

    attn = Sequential([
        ("ln", LayerNorm()),
        ("attn", MultiHeadAttention(num_heads, causal=causal,
                                    ring_axis=ring_axis,
                                    ring_axis_size=ring_axis_size)),
    ])
    if moe_experts:
        from .moe import MoE

        mlp = Sequential([
            ("ln", LayerNorm()),
            ("moe", MoE(moe_experts, hidden=dim * mlp_ratio,
                        capacity_factor=moe_capacity_factor)),
        ])
    else:
        mlp = Sequential([
            ("ln", LayerNorm()),
            ("fc1", Dense(dim * mlp_ratio)),
            ("gelu", Fn(_gelu, lambda s: s)),
            ("fc2", Dense(dim)),
        ])
    return Sequential([
        ("attn", Residual(attn, activation=None)),
        ("mlp", Residual(mlp, activation=None)),
    ])


class LSTM(Module):
    """Unidirectional LSTM via lax.scan: [B, T, D] -> [B, T, H]."""

    def __init__(self, hidden: int, reverse: bool = False):
        self.hidden = hidden
        self.reverse = reverse

    def init(self, rng, in_shape):
        import jax

        t, d = in_shape
        k1, k2 = _rng_split(rng, 2)
        h = self.hidden
        std_x = np.float32(1.0 / math.sqrt(d))
        std_h = np.float32(1.0 / math.sqrt(h))
        return {
            "wx": jax.random.normal(k1, (d, 4 * h), dtype=np.float32) * std_x,
            "wh": jax.random.normal(k2, (h, 4 * h), dtype=np.float32) * std_h,
            "b": np.zeros((4 * h,), np.float32),
        }, (t, h)

    def apply(self, params, x, train: bool = False):
        import jax
        import jax.numpy as jnp

        B, T, D = x.shape
        h = self.hidden
        wx, wh, b = (jnp.asarray(params[k]) for k in ("wx", "wh", "b"))
        # hoist the input projection out of the scan: one big MXU matmul
        xp = jnp.einsum("btd,dk->btk", x.astype(jnp.float32), wx) + b
        xp = jnp.swapaxes(xp, 0, 1)  # [T, B, 4H]

        def cell(carry, xt):
            hprev, cprev = carry
            gates = xt + hprev @ wh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * cprev + jax.nn.sigmoid(i) * jnp.tanh(g)
            hh = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (hh, c), hh

        zeros = jnp.zeros((B, h), dtype=jnp.float32)
        _, ys = jax.lax.scan(cell, (zeros, zeros), xp, reverse=self.reverse)
        return jnp.swapaxes(ys, 0, 1)  # [B, T, H]


class BiLSTM(Module):
    """Concat of forward and backward LSTM: [B, T, D] -> [B, T, 2H]
    (the CNTK BiLSTM tagger's core, TPU-native)."""

    def __init__(self, hidden: int):
        self.fwd = LSTM(hidden)
        self.bwd = LSTM(hidden, reverse=True)

    def init(self, rng, in_shape):
        k1, k2 = _rng_split(rng, 2)
        pf, (t, h) = self.fwd.init(k1, in_shape)
        pb, _ = self.bwd.init(k2, in_shape)
        return {"fwd": pf, "bwd": pb}, (t, 2 * h)

    def apply(self, params, x, train: bool = False):
        import jax.numpy as jnp

        return jnp.concatenate([self.fwd.apply(params["fwd"], x),
                                self.bwd.apply(params["bwd"], x)], axis=-1)


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

def transformer_encoder(seq_len: int, dim: int, depth: int, num_heads: int,
                        vocab_size: Optional[int] = None,
                        num_classes: Optional[int] = None,
                        causal: bool = False,
                        ring_axis: Optional[str] = None,
                        ring_axis_size: Optional[int] = None,
                        seed: int = 0):
    """Named-layer transformer encoder as a FunctionModel (taps address
    "block3", "block3/mlp/fc1", ... the way ResNet layers do)."""
    from .module import Dense, FunctionModel
    import jax

    layers = []
    if vocab_size is not None:
        layers.append(("embed", Embed(vocab_size, dim)))
        in_shape: Tuple[int, ...] = (seq_len,)
    else:
        in_shape = (seq_len, dim)
    for i in range(depth):
        layers.append((f"block{i}", transformer_block(
            dim, num_heads, causal=causal, ring_axis=ring_axis,
            ring_axis_size=ring_axis_size)))
    layers.append(("ln_f", LayerNorm()))
    if num_classes is not None:
        layers.append(("head", Dense(num_classes)))
    module = Sequential(layers, name="transformer")
    params, out_shape = module.init(jax.random.key(seed), in_shape)
    layer_names = [name for name, _ in reversed(layers)]
    return FunctionModel(module, params, in_shape, layer_names, "transformer")


def bilstm_tagger(seq_len: int, vocab_size: int, embed_dim: int,
                  hidden: int, num_tags: int, seed: int = 0):
    """Embed -> BiLSTM -> per-token tag logits (the medical entity
    extraction architecture, notebooks/DeepLearning - BiLSTM)."""
    from .module import Dense, FunctionModel
    import jax

    module = Sequential([
        ("embed", Embed(vocab_size, embed_dim)),
        ("bilstm", BiLSTM(hidden)),
        ("tags", Dense(num_tags)),
    ], name="bilstm_tagger")
    params, _ = module.init(jax.random.key(seed), (seq_len,))
    return FunctionModel(module, params, (seq_len,),
                         ["tags", "bilstm", "embed"], "bilstm_tagger")
