"""DNNModel — distributed DNN inference as a pipeline stage (CNTKModel parity).

The reference's north-star path (SURVEY §3.1, cntk/CNTKModel.scala:30-540):
broadcast a serialized CNTK graph to executors, minibatch rows, evaluate through JNI
per batch, unbatch, coerce outputs to vectors. The TPU-native redesign:

  - broadcast                → params resident on device(s); with a mesh, replicated
                               (or tensor-sharded) via NamedSharding once per transform.
  - per-row JNI eval loop    → one ``jax.jit``-compiled forward over a padded [B, ...]
                               batch; compile cache keyed by (output node, shape, dtype).
  - minibatcher              → parallel/batching.Minibatcher with power-of-two bucket
                               padding so XLA compiles O(log n) shapes (CNTKModel's
                               FixedMiniBatchTransformer default of batch 10 becomes a
                               static-shape batch: cntk/CNTKModel.scala:374,496-500).
  - feedDict/fetchDict       → input column -> model argument; output column <- named
                               node or OUTPUT_i (cntk/CNTKModel.scala:204-223 and
                               CNTK/SerializableFunction.scala:61-63,115-129).
  - output coercion          → per-row float32 vectors (CNTKModel.scala:462-483).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..core.params import ComplexParam, HasBatchSize, HasInputCol, HasOutputCol, Param
from ..core.dataframe import DataFrame
from ..core.pipeline import Model
from ..core.schema import ColType, Schema
from ..parallel.batching import Minibatcher, concat_outputs
from ..parallel.mesh import DATA_AXIS, MeshContext, data_sharding, replicated_sharding
from .module import FunctionModel


class DNNModel(Model, HasInputCol, HasOutputCol, HasBatchSize):
    """Evaluate a FunctionModel over an input column of arrays/images.

    Mirrors CNTKModel's public surface: setModel, setInputCol/setOutputCol (the
    1-input/1-output case of feedDict/fetchDict — CNTKModel.scala:204-260),
    setOutputNode/setOutputNodeIndex (SerializableFunction node addressing),
    setMiniBatchSize.
    """

    model = ComplexParam("model", "The FunctionModel to evaluate")
    outputNode = Param("outputNode", "Named layer to fetch (None = final output)", None, ptype=str)
    batchSize = Param("batchSize", "Rows per evaluation minibatch", 64, lambda v: v > 0, int)
    useMesh = Param("useMesh",
                    "Shard eval batches over the active mesh data axis; "
                    "None (default) = auto: on whenever a >1-device mesh has "
                    "been explicitly set via MeshContext.set, off otherwise. "
                    "True additionally builds a default mesh if none is set; "
                    "False forces single-device eval.", None, ptype=bool)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._jit_cache: Dict[Tuple, Any] = {}

    # -- fluent setters mirroring the reference API -----------------------
    def set_model(self, model: FunctionModel) -> "DNNModel":
        self._jit_cache.clear()  # compiled closures capture the model
        return self.set("model", model)

    def get_model(self) -> FunctionModel:
        return self.get_or_throw("model")

    def set_output_node(self, node: str) -> "DNNModel":
        return self.set("outputNode", node)

    def set_output_node_index(self, i: int) -> "DNNModel":
        return self.set("outputNode", f"OUTPUT_{i}")

    def set_mini_batch_size(self, n: int) -> "DNNModel":
        return self.set("batchSize", n)

    # -- compiled forward -------------------------------------------------
    def _compiled(self, tap: Optional[str]):
        """jit-compiled (params, x) -> activations for one fetch node."""
        import jax

        model = self.get_model()
        key = ("fwd", id(model), tap)
        if key not in self._jit_cache:

            def fwd(params, x):
                live = FunctionModel(model.module, params, model.input_shape,
                                     model.layer_names, model.name)
                return live.apply(x, tap=tap)

            self._jit_cache[key] = jax.jit(fwd)
        return self._jit_cache[key]

    def transform_schema(self, schema: Schema) -> Schema:
        schema.require(self.get_or_throw("inputCol"))
        out = schema.copy()
        out.types[self.get_or_throw("outputCol")] = ColType.VECTOR
        return out

    def transform(self, df: DataFrame) -> DataFrame:
        import jax

        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        model = self.get_model()
        tap = model.resolve_output(self.get("outputNode"))
        fwd = self._compiled(tap)
        batcher = Minibatcher(self.get("batchSize"), bucket=True, dtype=np.float32)

        params_dev = jax.device_put(model.params)  # resident once (broadcast parity)

        use = self.get("useMesh")
        mesh = MeshContext.get() if use is True else \
            (MeshContext.current() if use is None else None)
        sharding = None
        if mesh is not None and mesh.shape.get(DATA_AXIS, 1) > 1:
            sharding = data_sharding(mesh)
            params_dev = jax.device_put(params_dev, replicated_sharding(mesh))

        def eval_partition(part):
            n = len(part[in_col])
            col = np.empty(n, dtype=object)
            if n == 0:
                part[out_col] = col
                return part
            # null inputs produce null outputs (CNTKModel emits null rows for
            # undecodable inputs rather than failing the partition)
            in_vals = part[in_col]
            valid_idx = np.array([i for i in range(n) if in_vals[i] is not None],
                                 dtype=np.int64)
            if len(valid_idx) == 0:
                part[out_col] = col
                return part
            sub = {in_col: in_vals[valid_idx]}
            outs = []
            for batch in batcher.batches(sub, [in_col]):
                x = batch.arrays[in_col]
                if sharding is not None and x.shape[0] % mesh.shape[DATA_AXIS] == 0:
                    x = jax.device_put(x, sharding)
                y = np.asarray(fwd(params_dev, x), dtype=np.float32)
                outs.append(y[: batch.num_valid])
            full = concat_outputs(outs)
            for j, i in enumerate(valid_idx):
                col[i] = full[j]
            part[out_col] = col
            return part

        return df.map_partitions(eval_partition)
