"""DNNModel — distributed DNN inference as a pipeline stage (CNTKModel parity).

The reference's north-star path (SURVEY §3.1, cntk/CNTKModel.scala:30-540):
broadcast a serialized CNTK graph to executors, minibatch rows, evaluate through JNI
per batch, unbatch, coerce outputs to vectors. The TPU-native redesign:

  - broadcast                → params resident on device(s); with a mesh, replicated
                               (or tensor-sharded) via NamedSharding once per transform.
  - per-row JNI eval loop    → one ``jax.jit``-compiled forward over a padded [B, ...]
                               batch; compile cache keyed by (output node, shape, dtype).
  - minibatcher              → parallel/batching.Minibatcher with power-of-two bucket
                               padding so XLA compiles O(log n) shapes (CNTKModel's
                               FixedMiniBatchTransformer default of batch 10 becomes a
                               static-shape batch: cntk/CNTKModel.scala:374,496-500).
  - feedDict/fetchDict       → input column -> model argument; output column <- named
                               node or OUTPUT_i (cntk/CNTKModel.scala:204-223 and
                               CNTK/SerializableFunction.scala:61-63,115-129).
  - output coercion          → per-row float32 vectors (CNTKModel.scala:462-483).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..core.device_stage import DeviceFn
from ..core.params import ComplexParam, HasBatchSize, HasInputCol, HasOutputCol, Param
from ..core.dataframe import DataFrame
from ..core.pipeline import Model
from ..core.schema import ColType, Schema
from ..parallel.batching import Minibatcher, concat_outputs
from ..parallel.ingest import IngestStats, PreprocessSpec, TransferRing
from ..parallel.mesh import (DATA_AXIS, MeshContext, data_sharding,
                             fetch_global, replicated_sharding)
from .module import FunctionModel


class DNNModel(Model, HasInputCol, HasOutputCol, HasBatchSize):
    """Evaluate a FunctionModel over an input column of arrays/images.

    Mirrors CNTKModel's public surface: setModel, setInputCol/setOutputCol,
    setFeedDict/setFetchDict (multi-input / multi-output column<->node maps,
    all outputs fetched in ONE forward — CNTKModel.scala:204-260),
    setOutputNode/setOutputNodeIndex (SerializableFunction node addressing),
    setMiniBatchSize.
    """

    model = ComplexParam("model", "The FunctionModel to evaluate")
    outputNode = Param("outputNode", "Named layer to fetch (None = final output)", None, ptype=str)
    feedDict = Param("feedDict",
                     "Map of model argument names (ARGUMENT_i or graph input "
                     "names; keys) to input column names (values) — the "
                     "multi-input form of inputCol "
                     "(cntk/CNTKModel.scala:204-214)", None, ptype=dict)
    fetchDict = Param("fetchDict",
                      "Map of output column names (keys) to fetch nodes "
                      "(OUTPUT_i or layer paths; values) — the multi-output "
                      "form of outputCol, all fetched in ONE forward pass "
                      "(cntk/CNTKModel.scala:215-223)", None, ptype=dict)
    batchSize = Param("batchSize", "Rows per evaluation minibatch", 64, lambda v: v > 0, int)
    preprocess = ComplexParam(
        "preprocess",
        "PreprocessSpec fused into the compiled forward (cast/scale/offset/"
        "layout-transpose run on device, so input batches ride the host link "
        "in their wire dtype — uint8 pixels = 4x fewer H2D bytes). "
        "Single-input models only.")
    ringDepth = Param("ringDepth",
                      "In-flight batches in the transfer ring: the next "
                      "batches' H2D + compute overlap the previous fetch",
                      2, lambda v: v > 0, int)
    donateInputs = Param("donateInputs",
                         "Donate the input batch buffer into the compiled "
                         "step so XLA reuses the staging allocation. None "
                         "(default) = auto: on for accelerator backends, off "
                         "on CPU where donation is a no-op.", None, ptype=bool)
    useMesh = Param("useMesh",
                    "Shard eval batches over the active mesh data axis; "
                    "None (default) = auto: on whenever a >1-device mesh has "
                    "been explicitly set via MeshContext.set, off otherwise. "
                    "True additionally builds a default mesh if none is set; "
                    "False forces single-device eval.", None, ptype=bool)

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._jit_cache: Dict[Tuple, Any] = {}
        self._last_ingest_stats: Optional[IngestStats] = None

    @property
    def last_ingest_stats(self) -> Optional[IngestStats]:
        """Ingest decomposition of the most recent transform() (queue/h2d/
        compute/readback per batch, bytes, overlap ratio) — the e2e-vs-
        per-call gap as a measured quantity."""
        return self._last_ingest_stats

    # -- fluent setters mirroring the reference API -----------------------
    def set_model(self, model: FunctionModel) -> "DNNModel":
        self._jit_cache.clear()  # compiled closures capture the model
        return self.set("model", model)

    def get_model(self) -> FunctionModel:
        return self.get_or_throw("model")

    def set_output_node(self, node: str) -> "DNNModel":
        return self.set("outputNode", node)

    def set_output_node_index(self, i: int) -> "DNNModel":
        return self.set("outputNode", f"OUTPUT_{i}")

    def set_mini_batch_size(self, n: int) -> "DNNModel":
        return self.set("batchSize", n)

    def set_preprocess(self, spec: Optional[PreprocessSpec]) -> "DNNModel":
        return self.set("preprocess", spec)

    def set_ring_depth(self, n: int) -> "DNNModel":
        return self.set("ringDepth", n)

    def set_feed_dict(self, *args) -> "DNNModel":
        """set_feed_dict({arg: col, ...}) or set_feed_dict(arg, col)."""
        d = {args[0]: args[1]} if len(args) == 2 else dict(args[0])
        return self.set("feedDict", d)

    def set_fetch_dict(self, *args) -> "DNNModel":
        """set_fetch_dict({col: node, ...}) or set_fetch_dict(col, node)."""
        d = {args[0]: args[1]} if len(args) == 2 else dict(args[0])
        return self.set("fetchDict", d)

    # -- I/O maps ----------------------------------------------------------
    def _io_maps(self, model):
        """Resolve (input_name -> column, out_column -> tap) maps from either
        the dict params or the single-column params."""
        feed = self.get("feedDict")
        if feed:
            in_map = {model.resolve_input(k): v for k, v in feed.items()}
        else:
            in_map = {model.resolve_input("ARGUMENT_0"):
                      self.get_or_throw("inputCol")}
        fetch = self.get("fetchDict")
        if fetch:
            out_map = {c: model.resolve_output(n) for c, n in fetch.items()}
        else:
            out_map = {self.get_or_throw("outputCol"):
                       model.resolve_output(self.get("outputNode"))}
        return in_map, out_map

    # -- compiled forward -------------------------------------------------
    def _compiled(self, taps: Tuple[Optional[str], ...], multi_in: bool,
                  spec: Optional[PreprocessSpec] = None,
                  donate: bool = False):
        """jit-compiled (params, x) -> tuple of activations, one per tap
        (all fetched in ONE forward). ``x`` is an array, or a dict of arrays
        for multi-input models.

        ``spec``: PreprocessSpec fused ahead of the forward — the wire
        carries the raw batch dtype (uint8 pixels) and XLA folds the
        cast/scale/transpose into the first layer's own input cast.
        ``donate``: donate the batch argument so XLA reuses its staging
        buffer across steps (used only when the caller committed the batch
        to device; a no-op on CPU)."""
        import jax

        model = self.get_model()
        # even an identity-scale spec keeps its dtype cast: the wire batch
        # may be uint8 and the module must see spec.dtype (a float cast of
        # an already-float input is free in XLA)
        key = ("fwd", id(model), taps, multi_in, spec, donate)
        if key not in self._jit_cache:

            def fwd(params, x):
                if spec is not None:
                    x = spec.apply_device(x)
                live = FunctionModel(model.module, params, model.input_shape,
                                     model.layer_names, model.name)
                acts = live.apply_taps(x, list(taps))
                return tuple(acts[t] for t in taps)

            self._jit_cache[key] = jax.jit(
                fwd, donate_argnums=(1,)) if donate else jax.jit(fwd)
        return self._jit_cache[key]

    def device_fn(self, schema: Schema):
        """Fusion contract: single-input eval fuses as [optional
        PreprocessSpec] + ONE forward fetching every tap — the same traced
        jaxpr the unfused _compiled() path jits, so fused == unfused
        bitwise. Mesh-sharded eval and dict-feed (multi-input) models keep
        the unfused path."""
        model = self.get("model")
        if model is None or self.get("useMesh") is True:
            return None
        from ..parallel.mesh import DATA_AXIS, MeshContext

        mesh = MeshContext.current()
        if mesh is not None and mesh.shape.get(DATA_AXIS, 1) > 1:
            return None
        in_map, out_map = self._io_maps(model)
        if list(in_map) != model.argument_names()[:1]:
            return None  # multi-input feedDict eval stays unfused
        in_col = list(in_map.values())[0]
        out_cols = tuple(out_map)
        taps = tuple(out_map[c] for c in out_cols)
        spec: Optional[PreprocessSpec] = self.get("preprocess")
        # cache_token (not id): the shared CompileCache key must survive a
        # process restart for the fleet's persistent tier to hit
        key = ("DNNModel", model.cache_token(), in_col, out_cols, taps,
               None if spec is None else spec.cache_key())

        def fn(params, env):
            import jax.numpy as jnp

            x = env[in_col]
            if spec is not None:
                x = spec.apply_device(x)
            live = FunctionModel(model.module, params, model.input_shape,
                                 model.layer_names, model.name)
            acts = live.apply_taps(x, list(taps))
            # f32 on device == the unfused np.asarray(y, float32) readback
            return {c: acts[t].astype(jnp.float32)
                    for c, t in zip(out_cols, taps)}

        def accepts(probes):
            p = probes.get(in_col)
            if p is None or p["dtype"] is None:
                return True
            return p["sparse"] or p["dtype"].kind in "fuib"

        return DeviceFn(
            key=key, in_cols=(in_col,), out_cols=out_cols, fn=fn,
            params=model.params, accepts=accepts, reject_sparse=False,
            heavy=True,
            # pod-scale planner declaration (parallel/shardplan.py): flat
            # [N, F] feature inputs may shard their feature dim over the
            # mesh's tensor axis (GSPMD inserts the activation collectives)
            shard_dims={in_col: 1})

    def transform_schema(self, schema: Schema) -> Schema:
        if self.get("model") is None:
            # schema-only validation before the model is set: fall back to
            # the column params (node-name resolution needs a live model)
            feed = self.get("feedDict")
            in_cols = list(feed.values()) if feed \
                else [self.get_or_throw("inputCol")]
            fetch = self.get("fetchDict")
            out_cols = list(fetch) if fetch else [self.get_or_throw("outputCol")]
        else:
            model = self.get_model()
            in_map, out_map = self._io_maps(model)
            in_cols, out_cols = list(in_map.values()), list(out_map)
        for col in in_cols:
            schema.require(col)
        out = schema.copy()
        for col in out_cols:
            out.types[col] = ColType.VECTOR
        return out

    def transform(self, df: DataFrame) -> DataFrame:
        import jax

        model = self.get_model()
        in_map, out_map = self._io_maps(model)      # input name -> col, col -> tap
        in_cols = list(in_map.values())
        out_cols = list(out_map)
        taps = tuple(out_map[c] for c in out_cols)
        # dict-feed unless the map is exactly {primary input: col} — a single
        # entry naming a SECONDARY input must go through the dict path so
        # GraphModule validates the incomplete feed instead of silently
        # binding the column to the primary input
        multi_in = list(in_map) != model.argument_names()[:1]
        spec: Optional[PreprocessSpec] = self.get("preprocess")
        if spec is not None and multi_in:
            raise ValueError(
                "preprocess spec applies to single-input models only "
                "(feedDict consumers preprocess per column upstream)")
        fwd = self._compiled(taps, multi_in, spec)
        donate = self.get("donateInputs")
        if donate is None:
            donate = jax.default_backend() != "cpu"  # CPU donation is a no-op
        fwd_donated = self._compiled(taps, multi_in, spec, donate=True) \
            if donate else None
        batcher = Minibatcher(self.get("batchSize"), bucket=True,
                              dtype=np.float32, preserve_int=True)
        stats = IngestStats()
        self._last_ingest_stats = stats

        params_dev = jax.device_put(model.params)  # resident once (broadcast parity)

        use = self.get("useMesh")
        mesh = MeshContext.get() if use is True else \
            (MeshContext.current() if use is None else None)
        sharding = None
        if mesh is not None and mesh.shape.get(DATA_AXIS, 1) > 1:
            sharding = data_sharding(mesh)
            params_dev = jax.device_put(params_dev, replicated_sharding(mesh))

        def eval_partition(part):
            n = len(part[in_cols[0]])
            cols = {c: np.empty(n, dtype=object) for c in out_cols}
            if n == 0:
                for c in out_cols:
                    part[c] = cols[c]
                return part
            # null inputs produce null outputs (CNTKModel emits null rows for
            # undecodable inputs rather than failing the partition); a row is
            # valid only if EVERY fed column is non-null
            valid_idx = np.array(
                [i for i in range(n)
                 if all(part[c][i] is not None for c in in_cols)],
                dtype=np.int64)
            if len(valid_idx) == 0:
                for c in out_cols:
                    part[c] = cols[c]
                return part
            sub = {c: part[c][valid_idx] for c in in_cols}
            outs = []

            def to_device(batch):
                """Stack/pad + H2D for one batch — runs on the ring's
                prefetch thread so the NEXT batch's transfer overlaps this
                one's compute (DynamicBufferedBatcher parity,
                stages/Batchers.scala:12-160)."""
                if multi_in:
                    x = {name: batch.arrays[col]
                         for name, col in in_map.items()}
                    if sharding is not None:
                        # mesh-indivisible batches stay UNCOMMITTED host
                        # arrays (committing to one device conflicts with
                        # the mesh-replicated params inside jit)
                        if batch.size % mesh.shape[DATA_AXIS] == 0:
                            x = {k: jax.device_put(v, sharding)
                                 for k, v in x.items()}
                    else:
                        x = {k: jax.device_put(v) for k, v in x.items()}
                else:
                    x = batch.arrays[in_cols[0]]
                    if sharding is not None:
                        if x.shape[0] % mesh.shape[DATA_AXIS] == 0:
                            x = jax.device_put(x, sharding)
                    else:
                        x = jax.device_put(x)
                return x, batch.num_valid

            def step(staged):
                x, num_valid = staged
                # the donated executable only when the batch is device-
                # committed (uncommitted host arrays — the mesh-indivisible
                # case — have no staging buffer to reuse)
                leaves = list(x.values()) if isinstance(x, dict) else [x]
                f = fwd_donated if (fwd_donated is not None and
                                    all(isinstance(v, jax.Array)
                                        for v in leaves)) else fwd
                return f(params_dev, x), num_valid

            def fetch(handle):
                # fetch_global: under a multi-PROCESS mesh the sharded
                # output spans non-addressable devices (allgathered);
                # single-process it is a plain blocking readback
                ys, num_valid = handle
                return tuple(np.asarray(fetch_global(y),
                                        dtype=np.float32)[:num_valid]
                             for y in ys)

            ring = TransferRing(batcher.batches(sub, in_cols),
                                put=to_device, step=step, fetch=fetch,
                                depth=self.get("ringDepth"), stats=stats)
            try:
                for out in ring:
                    outs.append(out)
            finally:
                # a failed forward/readback must not strand the producer
                # thread blocked on the bounded queue (it pins device
                # buffers for the process lifetime)
                ring.close()
            for ci, c in enumerate(out_cols):
                full = concat_outputs([o[ci] for o in outs])
                for j, i in enumerate(valid_idx):
                    cols[c][i] = full[j]
            for c in out_cols:
                part[c] = cols[c]
            return part

        return df.map_partitions(eval_partition)
