"""GraphModule: executes an imported op graph (ONNX semantics) as a jit-pure Module.

This is the second half of the reference's external-model story: CNTK loads a serialized
graph and evaluates it natively with name-addressable nodes
(CNTK/SerializableFunction.scala:23-143, cntk/CNTKModel.scala:86-138). Here the imported
graph becomes a flat list of ops executed in topological order inside one traced
function — XLA sees the whole graph at once and fuses it like any hand-written model.

Layout note: ONNX convs/pools are NCHW. We keep NCHW *semantics* (bit-parity with the
source model, validated against torch) and let XLA's TPU layout assignment pick the
physical tiling — `conv_general_dilated` carries explicit dimension_numbers, so the
compiler is free to transpose internally; there is no per-op host cost.

Tap points: every node name is an addressable layer path (GraphModule.layer_paths), so
ImageFeaturizer's cutOutputLayers and DNNModel's fetch-node addressing work on imported
models exactly as on native ones.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .module import Module, Params


@dataclasses.dataclass
class GraphNode:
    """One op: ONNX op_type + attrs, resolved input/output tensor names."""

    name: str
    op_type: str
    inputs: List[str]
    outputs: List[str]
    attrs: Dict[str, Any]


def _pool_dims(x_shape, kernel, strides, pads, ceil_mode=False):
    """Output spatial dims for explicit-padded pooling (NCHW, 2 spatial dims)."""
    out = []
    for i in range(len(kernel)):
        size = x_shape[2 + i] + pads[i] + pads[i + len(kernel)] - kernel[i]
        if ceil_mode:
            out.append(-(-size // strides[i]) + 1)
        else:
            out.append(size // strides[i] + 1)
    return out


class GraphModule(Module):
    """A Module whose forward pass is an interpreted (but traced-once) op graph.

    ``params`` for this module is a flat dict {initializer_name: array}. The importer
    pre-populates it from the ONNX file; init() simply returns those arrays (with the
    rng ignored), so an imported model plugs into FunctionModel/DNNModel unchanged.
    """

    is_container = True

    def __init__(self, nodes: Sequence[GraphNode], initializers: Dict[str, np.ndarray],
                 input_name: str, output_name: str,
                 input_shape: Tuple[int, ...], name: str = "graph",
                 compute_dtype: str = "float32",
                 extra_input_shapes: Optional[Dict[str, Tuple[int, ...]]] = None,
                 extra_input_dtypes: Optional[Dict[str, Any]] = None,
                 input_dtype: Any = np.float32):
        self.nodes = list(nodes)
        self.initializers = {k: np.asarray(v) for k, v in initializers.items()}
        self.input_name = input_name
        self.output_name = output_name
        self.input_shape = tuple(input_shape)  # excludes batch dim, NCHW order for images
        # secondary graph inputs (multi-input models; feedDict parity):
        # {tensor_name: per-example shape}, ordered — ARGUMENT_1.. addressing
        self.extra_input_shapes = {
            k: tuple(v) for k, v in (extra_input_shapes or {}).items()}
        self.extra_input_dtypes = {
            k: np.dtype(v) for k, v in (extra_input_dtypes or {}).items()}
        self.input_dtype = np.dtype(input_dtype)
        self.name = name
        self.compute_dtype = compute_dtype

    @property
    def input_names(self) -> List[str]:
        return [self.input_name] + list(self.extra_input_shapes)

    # -- Module contract ----------------------------------------------------
    def init(self, rng, in_shape):
        import jax

        if tuple(in_shape) != self.input_shape:
            raise ValueError(
                f"GraphModule was imported for input shape {self.input_shape}, "
                f"got {tuple(in_shape)}")
        params = dict(self.initializers)
        primary_dt = np.dtype(np.int32) if self.input_dtype == np.int64 \
            else self.input_dtype
        x: Any = jax.ShapeDtypeStruct((1,) + self.input_shape, primary_dt)
        if self.extra_input_shapes:
            # multi-input probe: dynamic (None) secondary dims probed as 1
            x = {self.input_name: x}
            for name, shape in self.extra_input_shapes.items():
                dt = self.extra_input_dtypes.get(name, np.dtype(np.float32))
                # x64-off JAX: probe int64-declared inputs as int32
                if dt == np.int64:
                    dt = np.dtype(np.int32)
                x[name] = jax.ShapeDtypeStruct(
                    (1,) + tuple(1 if d is None else d for d in shape), dt)
        out = jax.eval_shape(
            lambda p, x: self.apply(p, x),
            {k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in params.items()},
            x)
        return params, tuple(out.shape[1:])

    def layer_paths(self, prefix: str = "") -> List[str]:
        return [f"{prefix}{n.name}" for n in self.nodes]

    def apply(self, params: Params, x, train: bool = False,
              taps: Optional[Set[str]] = None, taps_out: Optional[Dict[str, Any]] = None,
              stats_out: Optional[Dict[str, Any]] = None, _prefix: str = ""):
        import jax.numpy as jnp

        del train, stats_out  # imported graphs run inference-mode only
        _ensure_ops()
        env: Dict[str, Any] = dict(params)
        if isinstance(x, dict):
            missing = [n for n in self.input_names if n not in x]
            if missing:
                raise KeyError(f"graph inputs {missing} not fed "
                               f"(have {sorted(x)})")
            for name, arr in x.items():
                if self.compute_dtype != "float32" and not jnp.issubdtype(
                        jnp.asarray(arr).dtype, jnp.integer):
                    arr = arr.astype(self.compute_dtype)
                env[name] = arr
        else:
            if self.compute_dtype != "float32" and not jnp.issubdtype(
                    jnp.asarray(x).dtype, jnp.integer):
                x = x.astype(self.compute_dtype)
            env[self.input_name] = x
        for node in self.nodes:
            fn = _OPS.get(node.op_type)
            if fn is None:
                raise NotImplementedError(
                    f"ONNX op {node.op_type!r} (node {node.name!r}) is not supported; "
                    f"supported: {sorted(_OPS)}")
            args = [env[i] if i else None for i in node.inputs]
            res = fn(node, args, self.compute_dtype)
            if not isinstance(res, tuple):
                res = (res,)
            for out_name, val in zip(node.outputs, res):
                if out_name:
                    env[out_name] = val
            path = f"{_prefix}{node.name}"
            if taps is not None and taps_out is not None and path in taps:
                taps_out[path] = env[node.outputs[0]]
        out = env[self.output_name]
        if jnp.issubdtype(out.dtype, jnp.integer) or out.dtype == jnp.bool_:
            return out
        return out.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Op kernels. Each takes (node, args, compute_dtype) and returns array or tuple.
# Semantics follow the ONNX operator spec (opset 13 baseline; LayerNormalization
# per opset 17, Gelu per opset 20); coverage spans CNN, transformer
# (LayerNorm/Gelu/reduces/compares), decoder/segmentation (ConvTranspose,
# InstanceNorm, Resize), and recurrent (LSTM/GRU via lax.scan) families.
# Correctness is pinned by tests/test_onnx.py against torch reference forwards.
# ---------------------------------------------------------------------------


def _op_conv(node, args, cdt):
    import jax
    import jax.numpy as jnp

    x, w = args[0], args[1]
    b = args[2] if len(args) > 2 else None
    group = int(node.attrs.get("group", 1))
    strides = tuple(node.attrs.get("strides", [1] * (w.ndim - 2)))
    dilations = tuple(node.attrs.get("dilations", [1] * (w.ndim - 2)))
    nspatial = w.ndim - 2
    pads = node.attrs.get("pads")
    auto_pad = node.attrs.get("auto_pad", b"NOTSET")
    auto_pad = auto_pad.decode() if isinstance(auto_pad, bytes) else auto_pad
    if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        padding = "SAME"
    elif pads:
        padding = [(int(pads[i]), int(pads[i + nspatial])) for i in range(nspatial)]
    else:
        padding = [(0, 0)] * nspatial
    specs = {1: ("NCH", "OIH"), 2: ("NCHW", "OIHW"), 3: ("NCDHW", "OIDHW")}
    if nspatial not in specs:
        raise NotImplementedError(f"Conv with {nspatial} spatial dims")
    lhs_spec, rhs_spec = specs[nspatial]
    y = jax.lax.conv_general_dilated(
        x.astype(cdt), jnp.asarray(w).astype(cdt),
        window_strides=strides, padding=padding, rhs_dilation=dilations,
        dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
        feature_group_count=group,
        preferred_element_type=jnp.float32)
    y = y.astype(cdt)
    if b is not None:
        y = y + jnp.asarray(b).astype(y.dtype).reshape((1, -1) + (1,) * nspatial)
    return y


def _op_bn(node, args, cdt):
    import jax.numpy as jnp

    x, scale, bias, mean, var = args[:5]
    eps = float(node.attrs.get("epsilon", 1e-5))
    inv = jnp.asarray(scale) / jnp.sqrt(jnp.asarray(var).astype(np.float32) + eps)
    shift = jnp.asarray(bias) - jnp.asarray(mean) * inv
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return x * inv.reshape(shape).astype(x.dtype) + shift.reshape(shape).astype(x.dtype)


def _op_gemm(node, args, cdt):
    import jax.numpy as jnp

    a, b = args[0], args[1]
    c = args[2] if len(args) > 2 else None
    alpha = float(node.attrs.get("alpha", 1.0))
    beta = float(node.attrs.get("beta", 1.0))
    if int(node.attrs.get("transA", 0)):
        a = a.T
    if int(node.attrs.get("transB", 0)):
        b = jnp.asarray(b).T
    y = jnp.dot(a.astype(cdt), jnp.asarray(b).astype(cdt),
                preferred_element_type=jnp.float32).astype(jnp.float32)
    if alpha != 1.0:
        y = y * alpha
    if c is not None:
        y = y + beta * jnp.asarray(c)
    return y.astype(cdt)


def _window_op(node, args, cdt, reducer, init_val, is_avg=False):
    import jax
    import jax.numpy as jnp

    x = args[0]
    kernel = tuple(int(k) for k in node.attrs["kernel_shape"])
    nspatial = len(kernel)
    strides = tuple(int(s) for s in node.attrs.get("strides", [1] * nspatial))
    pads = node.attrs.get("pads", [0] * 2 * nspatial)
    auto_pad = node.attrs.get("auto_pad", b"NOTSET")
    auto_pad = auto_pad.decode() if isinstance(auto_pad, bytes) else auto_pad
    ceil_mode = int(node.attrs.get("ceil_mode", 0))
    if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
        padding = "SAME"
    else:
        padding = [(int(pads[i]), int(pads[i + nspatial])) for i in range(nspatial)]
        if ceil_mode and padding != "SAME":
            # grow right/bottom pad so ceil-divided windows fit
            for i in range(nspatial):
                size = x.shape[2 + i] + padding[i][0] + padding[i][1] - kernel[i]
                if size % strides[i]:
                    padding[i] = (padding[i][0],
                                  padding[i][1] + strides[i] - size % strides[i])
    window = (1, 1) + kernel
    strides_full = (1, 1) + strides
    pad_full = ([(0, 0), (0, 0)] + list(padding)) if padding != "SAME" else "SAME"
    if is_avg:
        ones = jnp.ones_like(x)
        s = jax.lax.reduce_window(x.astype(np.float32), 0.0, jax.lax.add,
                                  window, strides_full, pad_full)
        if int(node.attrs.get("count_include_pad", 0)):
            denom = float(np.prod(kernel))
            return (s / denom).astype(x.dtype)
        cnt = jax.lax.reduce_window(ones.astype(np.float32), 0.0, jax.lax.add,
                                    window, strides_full, pad_full)
        return (s / cnt).astype(x.dtype)
    return jax.lax.reduce_window(x, init_val, reducer, window, strides_full, pad_full)


def _op_maxpool(node, args, cdt):
    import jax

    return _window_op(node, args, cdt, jax.lax.max, -np.inf)


def _op_avgpool(node, args, cdt):
    return _window_op(node, args, cdt, None, 0.0, is_avg=True)


def _op_global_avgpool(node, args, cdt):
    import jax.numpy as jnp

    x = args[0]
    axes = tuple(range(2, x.ndim))
    return jnp.mean(x.astype(np.float32), axis=axes, keepdims=True).astype(x.dtype)


def _op_reshape(node, args, cdt):
    import jax.numpy as jnp

    x, shape = args[0], np.asarray(args[1]).tolist()
    # ONNX: 0 means "copy dim from input"; -1 infers
    shape = [x.shape[i] if s == 0 else int(s) for i, s in enumerate(shape)]
    return jnp.reshape(x, shape)


def _op_flatten(node, args, cdt):
    import jax.numpy as jnp

    x = args[0]
    axis = int(node.attrs.get("axis", 1))
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return jnp.reshape(x, (lead, -1))


def _op_concat(node, args, cdt):
    import jax.numpy as jnp

    return jnp.concatenate(args, axis=int(node.attrs.get("axis", 0)))


def _op_pad(node, args, cdt):
    import jax.numpy as jnp

    x = args[0]
    if len(args) > 1 and args[1] is not None:
        pads = np.asarray(args[1]).tolist()
    else:
        pads = node.attrs.get("pads", [0] * 2 * x.ndim)
    value = float(np.asarray(args[2])) if len(args) > 2 and args[2] is not None \
        else float(node.attrs.get("value", 0.0))
    mode = node.attrs.get("mode", b"constant")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    n = x.ndim
    widths = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
    if mode == "constant":
        return jnp.pad(x, widths, constant_values=value)
    return jnp.pad(x, widths, mode={"reflect": "reflect", "edge": "edge"}[mode])


def _op_clip(node, args, cdt):
    import jax.numpy as jnp

    x = args[0]
    lo = args[1] if len(args) > 1 and args[1] is not None else node.attrs.get("min")
    hi = args[2] if len(args) > 2 and args[2] is not None else node.attrs.get("max")
    if lo is not None:
        x = jnp.maximum(x, jnp.asarray(lo).astype(x.dtype))
    if hi is not None:
        x = jnp.minimum(x, jnp.asarray(hi).astype(x.dtype))
    return x


def _op_transpose(node, args, cdt):
    import jax.numpy as jnp

    perm = node.attrs.get("perm")
    return jnp.transpose(args[0], axes=perm)


def _op_softmax(node, args, cdt):
    import jax

    return jax.nn.softmax(args[0].astype(np.float32),
                          axis=int(node.attrs.get("axis", -1))).astype(args[0].dtype)


def _op_resize(node, args, cdt):
    import jax

    x = args[0]
    # inputs: X, roi, scales, sizes (opset 11+). Only nearest/linear on NCHW.
    sizes = args[3] if len(args) > 3 and args[3] is not None else None
    scales = args[2] if len(args) > 2 and args[2] is not None else None
    if sizes is not None:
        out_shape = tuple(int(s) for s in np.asarray(sizes).tolist())
    elif scales is not None:
        sc = np.asarray(scales).tolist()
        out_shape = tuple(int(round(d * s)) for d, s in zip(x.shape, sc))
    else:
        raise ValueError("Resize needs scales or sizes")
    mode = node.attrs.get("mode", b"nearest")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    method = {"nearest": "nearest", "linear": "bilinear", "cubic": "bicubic"}[mode]
    return jax.image.resize(x, out_shape, method=method)


def _unary(fn):
    return lambda node, args, cdt: fn(args[0])


def _binary(fn):
    return lambda node, args, cdt: fn(args[0], args[1])


def _make_ops() -> Dict[str, Callable]:
    import jax
    import jax.numpy as jnp

    return {
        "Conv": _op_conv,
        "BatchNormalization": _op_bn,
        "Gemm": _op_gemm,
        "MatMul": _binary(lambda a, b: jnp.matmul(
            a, b, preferred_element_type=jnp.float32).astype(a.dtype)),
        "MaxPool": _op_maxpool,
        "AveragePool": _op_avgpool,
        "GlobalAveragePool": _op_global_avgpool,
        "Relu": _unary(lambda x: jnp.maximum(x, 0)),
        "LeakyRelu": lambda n, a, c: jnp.where(
            a[0] > 0, a[0], a[0] * np.float32(n.attrs.get("alpha", 0.01))),
        "Sigmoid": _unary(jax.nn.sigmoid),
        "HardSigmoid": lambda n, a, c: jnp.clip(
            a[0] * np.float32(n.attrs.get("alpha", 0.2))
            + np.float32(n.attrs.get("beta", 0.5)), 0, 1),
        "HardSwish": _unary(jax.nn.hard_swish),
        "Tanh": _unary(jnp.tanh),
        "Erf": _unary(jax.lax.erf),
        "Exp": _unary(jnp.exp),
        "Sqrt": _unary(jnp.sqrt),
        "Reciprocal": _unary(jnp.reciprocal),
        "Neg": _unary(jnp.negative),
        "Abs": _unary(jnp.abs),
        "Softmax": _op_softmax,
        "Add": _binary(jnp.add),
        "Sub": _binary(jnp.subtract),
        "Mul": _binary(jnp.multiply),
        "Div": _binary(jnp.divide),
        "Pow": _binary(jnp.power),
        "Min": lambda n, a, c: jnp.minimum(a[0], a[1]),
        "Max": lambda n, a, c: jnp.maximum(a[0], a[1]),
        "Concat": _op_concat,
        "Reshape": _op_reshape,
        "Flatten": _op_flatten,
        "Transpose": _op_transpose,
        "Pad": _op_pad,
        "Clip": _op_clip,
        "Identity": _unary(lambda x: x),
        "Dropout": lambda n, a, c: a[0],  # inference mode
        "Cast": lambda n, a, c: a[0].astype(
            {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
             10: np.float16, 11: np.float64}[int(n.attrs.get("to", 1))]),
        "ReduceMean": _reduce(lambda x, axis, keepdims: jnp.mean(
            x, axis=axis, keepdims=keepdims)),
        "ReduceSum": _reduce(lambda x, axis, keepdims: jnp.sum(
            x, axis=axis, keepdims=keepdims)),
        "ReduceMax": _reduce(lambda x, axis, keepdims: jnp.max(
            x, axis=axis, keepdims=keepdims)),
        "ReduceMin": _reduce(lambda x, axis, keepdims: jnp.min(
            x, axis=axis, keepdims=keepdims)),
        "ReduceProd": _reduce(lambda x, axis, keepdims: jnp.prod(
            x, axis=axis, keepdims=keepdims)),
        "ArgMax": _argminmax(jnp.argmax),
        "ArgMin": _argminmax(jnp.argmin),
        "LayerNormalization": _op_layernorm,
        "InstanceNormalization": _op_instancenorm,
        "ConvTranspose": _op_conv_transpose,
        "GlobalMaxPool": lambda n, a, c: jnp.max(
            a[0], axis=tuple(range(2, a[0].ndim)), keepdims=True),
        "Gelu": lambda n, a, c: (
            jax.nn.gelu(a[0].astype(np.float32),
                        approximate=(n.attrs.get("approximate", b"none")
                                     in (b"tanh", "tanh"))).astype(a[0].dtype)),
        "Softplus": _unary(lambda x: jax.nn.softplus(
            x.astype(np.float32)).astype(x.dtype)),
        "Elu": lambda n, a, c: jnp.where(
            a[0] > 0, a[0],
            np.float32(n.attrs.get("alpha", 1.0))
            * (jnp.exp(jnp.minimum(a[0], 0.0)) - 1)),
        "Selu": lambda n, a, c: (
            np.float32(n.attrs.get("gamma", 1.0507009873554805))
            * jnp.where(a[0] > 0, a[0],
                        np.float32(n.attrs.get("alpha", 1.6732632423543772))
                        * (jnp.exp(jnp.minimum(a[0], 0.0)) - 1))),
        "PRelu": _binary(lambda x, s: jnp.where(x > 0, x, x * s)),
        "Expand": _op_expand,
        "Tile": _op_tile,
        "Where": lambda n, a, c: jnp.where(a[0], a[1], a[2]),
        "Equal": _binary(jnp.equal),
        "Greater": _binary(jnp.greater),
        "GreaterOrEqual": _binary(jnp.greater_equal),
        "Less": _binary(jnp.less),
        "LessOrEqual": _binary(jnp.less_equal),
        "Not": _unary(jnp.logical_not),
        "And": _binary(jnp.logical_and),
        "Or": _binary(jnp.logical_or),
        "Log": _unary(jnp.log),
        "Sin": _unary(jnp.sin),
        "Cos": _unary(jnp.cos),
        "Floor": _unary(jnp.floor),
        "Ceil": _unary(jnp.ceil),
        "Round": _unary(jnp.round),
        "Sign": _unary(jnp.sign),
        "Mean": lambda n, a, c: sum(a) / len(a),
        "Sum": lambda n, a, c: sum(a),
        "LSTM": _op_lstm,
        "GRU": _op_gru,
        "Resize": _op_resize,
        "Shape": lambda n, a, c: jnp.asarray(a[0].shape, dtype=jnp.int64),
        "Gather": lambda n, a, c: jnp.take(
            a[0], jnp.asarray(a[1]), axis=int(n.attrs.get("axis", 0))),
        "Unsqueeze": lambda n, a, c: jnp.expand_dims(
            a[0], tuple(int(x) for x in (
                n.attrs.get("axes") or np.asarray(a[1]).tolist()))),
        "Squeeze": lambda n, a, c: jnp.squeeze(
            a[0], tuple(int(x) for x in (
                n.attrs.get("axes") or np.asarray(a[1]).tolist()))),
        "Slice": _op_slice,
        "Split": _op_split,
    }


def _op_layernorm(node, args, cdt):
    import jax.numpy as jnp

    x, scale = args[0], jnp.asarray(args[1])
    b = jnp.asarray(args[2]) if len(args) > 2 and args[2] is not None else None
    axis = int(node.attrs.get("axis", -1))
    eps = float(node.attrs.get("epsilon", 1e-5))
    axes = tuple(range(axis % x.ndim, x.ndim))
    xf = x.astype(np.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    inv = 1.0 / jnp.sqrt(var + eps)
    y = ((xf - mean) * inv * scale.astype(np.float32))
    if b is not None:
        y = y + b.astype(np.float32)
    # spec outputs: Y, Mean, InvStdDev (later two rarely consumed)
    return y.astype(x.dtype), mean, inv


def _op_instancenorm(node, args, cdt):
    import jax.numpy as jnp

    x, scale, b = args[0], jnp.asarray(args[1]), jnp.asarray(args[2])
    eps = float(node.attrs.get("epsilon", 1e-5))
    axes = tuple(range(2, x.ndim))
    xf = x.astype(np.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    y = (xf - mean) / jnp.sqrt(var + eps) * scale.reshape(shape) \
        + b.reshape(shape)
    return y.astype(x.dtype)


def _op_conv_transpose(node, args, cdt):
    import jax
    import jax.numpy as jnp

    x, w = args[0], jnp.asarray(args[1])
    b = args[2] if len(args) > 2 else None
    group = int(node.attrs.get("group", 1))
    nspatial = w.ndim - 2
    auto_pad = node.attrs.get("auto_pad", b"NOTSET")
    auto_pad = auto_pad.decode() if isinstance(auto_pad, bytes) else auto_pad
    if auto_pad not in ("NOTSET", ""):
        raise NotImplementedError(f"ConvTranspose auto_pad={auto_pad!r}")
    if node.attrs.get("output_shape"):
        raise NotImplementedError("ConvTranspose output_shape attribute")
    strides = [int(s) for s in node.attrs.get("strides", [1] * nspatial)]
    dilations = [int(d) for d in node.attrs.get("dilations", [1] * nspatial)]
    out_pad = [int(p) for p in node.attrs.get("output_padding", [0] * nspatial)]
    pads = node.attrs.get("pads", [0] * 2 * nspatial)
    kernel = [int(k) for k in w.shape[2:]]

    # ONNX W layout: [C_in, C_out/group, k...]. Express the transposed conv as
    # a dilated-input forward conv: flip the kernel spatially, swap in/out
    # channel axes (per group), dilate the input by the stride, and pad so
    # out = (i-1)*s - pb - pe + ((k-1)*d + 1) + output_padding.
    wg = w.reshape((group, w.shape[0] // group) + tuple(w.shape[1:]))
    wg = jnp.flip(wg, axis=tuple(range(3, 3 + nspatial)))
    wg = jnp.swapaxes(wg, 1, 2)  # [g, C_out/g, C_in/g, k...]
    w_fwd = wg.reshape((w.shape[1] * group, w.shape[0] // group) + tuple(kernel))

    padding = []
    for i in range(nspatial):
        eff_k = (kernel[i] - 1) * dilations[i]
        padding.append((eff_k - int(pads[i]),
                        eff_k - int(pads[i + nspatial]) + out_pad[i]))
    specs = {1: ("NCH", "OIH"), 2: ("NCHW", "OIHW"), 3: ("NCDHW", "OIDHW")}
    lhs_spec, rhs_spec = specs[nspatial]
    y = jax.lax.conv_general_dilated(
        x.astype(cdt), w_fwd.astype(cdt),
        window_strides=(1,) * nspatial, padding=padding,
        lhs_dilation=tuple(strides), rhs_dilation=tuple(dilations),
        dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
        feature_group_count=group,
        preferred_element_type=jnp.float32)
    y = y.astype(cdt)
    if b is not None:
        y = y + jnp.asarray(b).astype(y.dtype).reshape(
            (1, -1) + (1,) * nspatial)
    return y


def _reduce(fn):
    def op(node, args, cdt):
        import jax.numpy as jnp

        axes = node.attrs.get("axes")
        if axes is None and len(args) > 1 and args[1] is not None:
            axes = np.asarray(args[1]).tolist()
        keepdims = bool(node.attrs.get("keepdims", 1))
        if not axes and int(node.attrs.get("noop_with_empty_axes", 0)):
            return args[0]  # spec: empty/absent axes + noop flag = identity
        return fn(args[0], axis=tuple(int(a) for a in axes) if axes else None,
                  keepdims=keepdims)
    return op


def _argminmax(fn):
    def op(node, args, cdt):
        import jax.numpy as jnp

        axis = int(node.attrs.get("axis", 0))
        keepdims = bool(node.attrs.get("keepdims", 1))
        x = args[0]
        if int(node.attrs.get("select_last_index", 0)):
            # spec: ties pick the LAST index — flip, argmax, re-index
            out = x.shape[axis] - 1 - fn(jnp.flip(x, axis), axis=axis)
        else:
            out = fn(x, axis=axis)
        if keepdims:
            out = jnp.expand_dims(out, axis)
        # spec says int64; int32 under JAX's default x64-off (same values)
        return out.astype(jnp.int32)
    return op


def _op_expand(node, args, cdt):
    import jax.numpy as jnp

    x = args[0]
    shape = [int(s) for s in np.asarray(args[1]).tolist()]
    # ONNX Expand: bidirectional broadcast; dim 1 (or missing) broadcasts
    want = list(jnp.broadcast_shapes(tuple(x.shape), tuple(shape)))
    return jnp.broadcast_to(x, want)


def _op_tile(node, args, cdt):
    import jax.numpy as jnp

    reps = [int(r) for r in np.asarray(args[1]).tolist()]
    return jnp.tile(args[0], reps)


def _lstm_gates(x_t, h, c, w, r, wb, rb):
    """One ONNX LSTM step; gate order iofc, activations sigmoid/tanh/tanh."""
    import jax
    import jax.numpy as jnp

    H = h.shape[-1]
    z = x_t @ w.T + h @ r.T + wb + rb            # [B, 4H]
    i = jax.nn.sigmoid(z[:, :H])
    o = jax.nn.sigmoid(z[:, H:2 * H])
    f = jax.nn.sigmoid(z[:, 2 * H:3 * H])
    g = jnp.tanh(z[:, 3 * H:])
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def _op_lstm(node, args, cdt):
    """ONNX LSTM (layout 0: X [T,B,I]) via lax.scan; supports forward /
    reverse / bidirectional, default activations, optional B/initial_h/c.
    sequence_lens is ignored (all sequences full length)."""
    import jax
    import jax.numpy as jnp

    x, w, r = args[0], jnp.asarray(args[1]), jnp.asarray(args[2])
    hidden = int(node.attrs["hidden_size"])
    direction = node.attrs.get("direction", b"forward")
    direction = direction.decode() if isinstance(direction, bytes) else direction
    if int(node.attrs.get("layout", 0)) != 0:
        raise NotImplementedError("LSTM layout=1")
    if len(args) > 7 and args[7] is not None:
        raise NotImplementedError("LSTM peephole weights (input P)")
    T, B, _ = x.shape
    D = w.shape[0]
    bias = jnp.asarray(args[3]) if len(args) > 3 and args[3] is not None \
        else jnp.zeros((D, 8 * hidden), dtype=jnp.float32)
    h0 = jnp.asarray(args[5]) if len(args) > 5 and args[5] is not None \
        else jnp.zeros((D, B, hidden), dtype=jnp.float32)
    c0 = jnp.asarray(args[6]) if len(args) > 6 and args[6] is not None \
        else jnp.zeros((D, B, hidden), dtype=jnp.float32)

    xf = x.astype(np.float32)
    dirs = {"forward": [False], "reverse": [True],
            "bidirectional": [False, True]}[direction]
    ys, hs, cs = [], [], []
    for d, rev in enumerate(dirs):
        seq = jnp.flip(xf, 0) if rev else xf
        wb, rb = bias[d, :4 * hidden], bias[d, 4 * hidden:]

        def step(carry, x_t, _w=w[d], _r=r[d], _wb=wb, _rb=rb):
            h, c = carry
            h2, c2 = _lstm_gates(x_t, h, c, _w, _r, _wb, _rb)
            return (h2, c2), h2

        (h_fin, c_fin), y = jax.lax.scan(step, (h0[d], c0[d]), seq)
        ys.append(jnp.flip(y, 0) if rev else y)
        hs.append(h_fin)
        cs.append(c_fin)
    Y = jnp.stack(ys, axis=1)                     # [T, D, B, H]
    return Y.astype(x.dtype), jnp.stack(hs, 0), jnp.stack(cs, 0)


def _op_gru(node, args, cdt):
    """ONNX GRU (layout 0), gate order zrh; honors linear_before_reset."""
    import jax
    import jax.numpy as jnp

    x, w, r = args[0], jnp.asarray(args[1]), jnp.asarray(args[2])
    hidden = int(node.attrs["hidden_size"])
    direction = node.attrs.get("direction", b"forward")
    direction = direction.decode() if isinstance(direction, bytes) else direction
    lbr = int(node.attrs.get("linear_before_reset", 0))
    if int(node.attrs.get("layout", 0)) != 0:
        raise NotImplementedError("GRU layout=1")
    T, B, _ = x.shape
    D = w.shape[0]
    bias = jnp.asarray(args[3]) if len(args) > 3 and args[3] is not None \
        else jnp.zeros((D, 6 * hidden), dtype=jnp.float32)
    h0 = jnp.asarray(args[5]) if len(args) > 5 and args[5] is not None \
        else jnp.zeros((D, B, hidden), dtype=jnp.float32)

    xf = x.astype(np.float32)
    dirs = {"forward": [False], "reverse": [True],
            "bidirectional": [False, True]}[direction]
    ys, hs = [], []
    H = hidden
    for d, rev in enumerate(dirs):
        seq = jnp.flip(xf, 0) if rev else xf
        wb, rb = bias[d, :3 * H], bias[d, 3 * H:]

        def step(carry, x_t, _w=w[d], _r=r[d], _wb=wb, _rb=rb):
            h = carry
            xz = x_t @ _w.T + _wb                 # [B, 3H]
            hz = h @ _r.T                         # [B, 3H] (no rb yet)
            z = jax.nn.sigmoid(xz[:, :H] + hz[:, :H] + _rb[:H])
            rt = jax.nn.sigmoid(xz[:, H:2 * H] + hz[:, H:2 * H] + _rb[H:2 * H])
            if lbr:
                ht = jnp.tanh(xz[:, 2 * H:] + rt * (hz[:, 2 * H:] + _rb[2 * H:]))
            else:
                ht = jnp.tanh(xz[:, 2 * H:] + (rt * h) @ _r[2 * H:].T
                              + _rb[2 * H:])
            h2 = (1 - z) * ht + z * h
            return h2, h2

        h_fin, y = jax.lax.scan(step, h0[d], seq)
        ys.append(jnp.flip(y, 0) if rev else y)
        hs.append(h_fin)
    Y = jnp.stack(ys, axis=1)
    return Y.astype(x.dtype), jnp.stack(hs, 0)


def _op_slice(node, args, cdt):
    x = args[0]
    if "starts" in node.attrs:  # opset 1-9 attribute form
        starts = node.attrs["starts"]
        ends = node.attrs["ends"]
        axes = node.attrs.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    else:
        starts = np.asarray(args[1]).tolist()
        ends = np.asarray(args[2]).tolist()
        axes = (np.asarray(args[3]).tolist() if len(args) > 3 and args[3] is not None
                else list(range(len(starts))))
        steps = (np.asarray(args[4]).tolist() if len(args) > 4 and args[4] is not None
                 else [1] * len(starts))
    idx: List[Any] = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        idx[int(a)] = slice(int(s) if s > -2**62 else None,
                            int(e) if abs(e) < 2**62 else None, int(st))
    return x[tuple(idx)]


def _op_split(node, args, cdt):
    import jax.numpy as jnp

    x = args[0]
    axis = int(node.attrs.get("axis", 0))
    split = node.attrs.get("split")
    if split is None and len(args) > 1 and args[1] is not None:
        split = np.asarray(args[1]).tolist()
    if split is None:
        n_out = len(node.outputs)
        return tuple(jnp.split(x, n_out, axis=axis))
    points = np.cumsum(split)[:-1].tolist()
    return tuple(jnp.split(x, points, axis=axis))


# op table built lazily on first apply (jax import deferred like the rest of module.py)
_OPS: Dict[str, Callable] = {}


def _ensure_ops() -> None:
    if not _OPS:
        _OPS.update(_make_ops())
