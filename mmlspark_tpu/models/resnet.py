"""ResNet family, TPU-first (NHWC, bf16 matmuls, static shapes).

The flagship DNN for the framework's north-star path (SURVEY §3.1/§3.5): the
reference featurizes images through pretrained CNTK CNNs (ResNet-50 in
`notebooks/samples` and `downloader/Schema.scala` model repo); here the ResNet is a
native JAX module whose intermediate layers are addressable by name so
ImageFeaturizer's ``cutOutputLayers`` works identically
(image/ImageFeaturizer.scala:133-178).

Builders return a :class:`~mmlspark_tpu.models.module.FunctionModel` with
``layer_names`` ordered head-first: ``["fc", "avgpool", "layer4", ...]`` — so
``cutOutputLayers=1`` yields the 2048-d pooled embedding, matching the reference's
convention of cutting N layers off the top (downloader/Schema.scala:44-100).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from .module import (
    BatchNorm,
    Conv2D,
    Dense,
    Fn,
    FunctionModel,
    GlobalAvgPool,
    MaxPool,
    Residual,
    Sequential,
    flatten,
    relu,
)


def _pad(k: int, torch_style: bool):
    """XLA "SAME" vs torch's symmetric k//2 pad: identical at stride 1, but at stride 2
    SAME splits the remainder (0,1) where torch pads (1,1) — explicit pads give exact
    transplant parity (torch_import.py)."""
    return ((k // 2, k // 2),) * 2 if torch_style else "SAME"


def _bottleneck(in_ch: int, mid_ch: int, stride: int,
                torch_padding: bool = False) -> Residual:
    out_ch = mid_ch * 4
    body = Sequential([
        ("conv1", Conv2D(mid_ch, (1, 1))),
        ("bn1", BatchNorm()),
        ("relu1", relu()),
        ("conv2", Conv2D(mid_ch, (3, 3), (stride, stride), _pad(3, torch_padding))),
        ("bn2", BatchNorm()),
        ("relu2", relu()),
        ("conv3", Conv2D(out_ch, (1, 1))),
        ("bn3", BatchNorm()),
    ])
    shortcut = None
    if stride != 1 or in_ch != out_ch:
        shortcut = Sequential([
            ("conv", Conv2D(out_ch, (1, 1), (stride, stride))),
            ("bn", BatchNorm()),
        ])
    return Residual(body, shortcut)


def _basic(in_ch: int, out_ch: int, stride: int,
           torch_padding: bool = False) -> Residual:
    body = Sequential([
        ("conv1", Conv2D(out_ch, (3, 3), (stride, stride), _pad(3, torch_padding))),
        ("bn1", BatchNorm()),
        ("relu1", relu()),
        ("conv2", Conv2D(out_ch, (3, 3), padding=_pad(3, torch_padding))),
        ("bn2", BatchNorm()),
    ])
    shortcut = None
    if stride != 1 or in_ch != out_ch:
        shortcut = Sequential([
            ("conv", Conv2D(out_ch, (1, 1), (stride, stride))),
            ("bn", BatchNorm()),
        ])
    return Residual(body, shortcut)


_CONFIGS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
}


def build_resnet(depth: int = 50, num_classes: int = 1000,
                 image_size: int = 224, channels: int = 3,
                 width: int = 64, torch_padding: bool = False) -> Sequential:
    kind, blocks = _CONFIGS[depth]
    expansion = 4 if kind == "bottleneck" else 1
    layers: List[Tuple[str, "Sequential"]] = [
        ("stem", Sequential([
            ("conv", Conv2D(width, (7, 7), (2, 2), _pad(7, torch_padding))),
            ("bn", BatchNorm()),
            ("relu", relu()),
            ("pool", MaxPool((3, 3), (2, 2),
                             ((1, 1), (1, 1)) if torch_padding else "SAME")),
        ])),
    ]
    in_ch = width
    for i, n in enumerate(blocks):
        ch = width * (2 ** i)
        stage = []
        for j in range(n):
            stride = 2 if (i > 0 and j == 0) else 1
            if kind == "bottleneck":
                stage.append((str(j), _bottleneck(in_ch, ch, stride, torch_padding)))
                in_ch = ch * expansion
            else:
                stage.append((str(j), _basic(in_ch, ch, stride, torch_padding)))
                in_ch = ch
        layers.append((f"layer{i + 1}", Sequential(stage)))
    layers.append(("avgpool", GlobalAvgPool()))
    layers.append(("fc", Dense(num_classes)))
    return Sequential(layers, name=f"resnet{depth}")


def resnet(depth: int = 50, num_classes: int = 1000, image_size: int = 224,
           channels: int = 3, seed: int = 0, width: int = 64) -> FunctionModel:
    """Build + initialize a ResNet FunctionModel."""
    import jax

    module = build_resnet(depth, num_classes, image_size, channels, width)
    rng = jax.random.PRNGKey(seed)
    params, out_shape = module.init(rng, (image_size, image_size, channels))
    if out_shape != (num_classes,):
        raise RuntimeError(
            f"resnet head produced shape {out_shape}, expected "
            f"({num_classes},) — build_resnet/init disagree")
    layer_names = ["fc", "avgpool", "layer4", "layer3", "layer2", "layer1", "stem"]
    return FunctionModel(module=module, params=params,
                         input_shape=(image_size, image_size, channels),
                         layer_names=layer_names, name=f"resnet{depth}")


def resnet50(num_classes: int = 1000, image_size: int = 224, seed: int = 0) -> FunctionModel:
    return resnet(50, num_classes, image_size, seed=seed)


def resnet18(num_classes: int = 1000, image_size: int = 224, seed: int = 0) -> FunctionModel:
    return resnet(18, num_classes, image_size, seed=seed)


def param_shardings(params, mesh):
    """NamedSharding rules for ResNet params on a mesh.

    Conv kernels [kh,kw,cin,cout] shard cout over the ``tensor`` axis; dense kernels
    [din,dout] shard dout; 1-D vectors replicate. With tensor=1 meshes this degrades
    to full replication — the mesh-agnostic default (scaling-book style: annotate,
    let XLA insert collectives).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def rule(leaf):
        if leaf.ndim == 4:
            return NamedSharding(mesh, P(None, None, None, "tensor"))
        if leaf.ndim == 2:
            return NamedSharding(mesh, P(None, "tensor"))
        return NamedSharding(mesh, P())

    return jax.tree.map(rule, params)
