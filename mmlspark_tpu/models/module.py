"""Minimal functional NN module system with named-layer addressability.

TPU-native replacement for the reference's CNTK graph engine (the C++ evaluation
engine driven through CNTK/SerializableFunction.scala:23-143). Design goals:

  - **Pure-functional**: a module is a pair of pure functions ``init(rng, shape)`` and
    ``apply(params, x)``; params are pytrees of jax/numpy arrays, so the whole forward
    pass jits and shards with `jax.jit`/`shard_map` — no graph VM, XLA *is* the engine.
  - **Named-layer tap points**: every layer has a path name ("stem/conv", "layer4/2/relu").
    ``apply(..., taps={...})`` returns intermediate activations by name. This gives the
    reference's node-addressing semantics (`SerializableFunction.scala:61-63,115-129`:
    name-based feed/fetch plus positional ``ARGUMENT_i``/``OUTPUT_i``) and powers
    ImageFeaturizer's ``cutOutputLayers`` (image/ImageFeaturizer.scala:133-178).
  - **bf16 compute, f32 params**: matmul/conv inputs cast to bfloat16 for the MXU;
    accumulation and parameters stay float32.

No flax dependency: the module tree is plain Python objects (picklable = serializable
via core/serialize.py), params are plain nested dicts.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

Params = Dict[str, Any]


import contextvars as _contextvars

_MATMUL_DTYPE: "_contextvars.ContextVar[str]" = _contextvars.ContextVar(
    "mmlspark_tpu_matmul_dtype", default="bfloat16")


def matmul_dtype() -> str:
    """Activation/weight dtype for Conv2D/Dense MXU ops: "bfloat16" (default —
    half the HBM traffic; accumulation is always f32), "float32" (exact —
    used by sharded-equals-single-device equivalence tests and accuracy-parity
    gates, where bf16 rounding noise would mask real sharding bugs), or
    "float64" (numerical experiments; requires jax_enable_x64)."""
    return _MATMUL_DTYPE.get()


class _ContextVarScope:
    """Context manager setting a ContextVar for the scope (thread/task-local,
    so concurrent jit traces can't leak each other's setting)."""

    _var: "_contextvars.ContextVar"

    def __init__(self, value):
        self._value = value

    def __enter__(self):
        self._token = self._var.set(self._value)
        return self

    def __exit__(self, *exc):
        self._var.reset(self._token)
        return False


class matmul_precision(_ContextVarScope):
    """Context manager selecting the matmul dtype, read at TRACE time.

    CAUTION: jit retraces read the dtype current at the retrace — a function
    first traced inside ``matmul_precision("float32")`` that later retraces
    (new input shapes) OUTSIDE the context compiles those shapes in the
    then-current default. Keep every call that may trace inside the context
    (or bake the precision in with a trace-time wrapper the way
    compile_train_step does for activation sharding)."""

    _var = _MATMUL_DTYPE

    def __init__(self, dtype: str):
        if dtype not in ("bfloat16", "float32", "float64"):
            raise ValueError(
                f"matmul_precision: unknown dtype {dtype!r} "
                f"(expected bfloat16/float32/float64)")
        if dtype == "float64":
            import jax
            if not jax.config.jax_enable_x64:
                raise RuntimeError(
                    "matmul_precision('float64') requires jax_enable_x64 "
                    "(otherwise astype(float64) silently yields float32)")
        super().__init__(dtype)


_ACTIVATION_SHARDING = _contextvars.ContextVar(
    "mmlspark_tpu_activation_sharding", default=None)


class activation_sharding(_ContextVarScope):
    """Trace-time context: constrain every inter-layer activation to the given
    sharding (normally batch_sharding(mesh)).

    Why this exists: the XLA SPMD partitioners (both Shardy and legacy GSPMD)
    mis-propagate the BACKWARD of conv when a broadcast-multiply sits between
    two channel-sharded convs at small spatial sizes — gradients come back
    wrong by ~1e-1 in f64 (repro: tests/test_models.py
    test_train_step_dp_fsdp_tp_matches_single_device, which fails without
    this). Anchoring each activation to the batch sharding removes the bad
    propagation choice; with the anchors, sharded == single-device to 1e-7.
    compile_train_step(mesh=...) enables it automatically, inside the traced
    function so retraces re-enter it.
    """

    _var = _ACTIVATION_SHARDING


def _constrain_activation(x):
    s = _ACTIVATION_SHARDING.get()
    if s is None:
        return x
    import jax
    return jax.lax.with_sharding_constraint(x, s)


def _rng_split(rng, n):
    import jax
    return jax.random.split(rng, n)


class Module:
    """Base module. Subclasses implement init/apply; both must be jit-pure."""

    name: str = ""

    def init(self, rng, in_shape: Tuple[int, ...]) -> Tuple[Params, Tuple[int, ...]]:
        """Returns (params, out_shape). Shapes exclude the batch dim."""
        raise NotImplementedError

    def apply(self, params: Params, x, train: bool = False):
        raise NotImplementedError

    # -- graph introspection ------------------------------------------------
    def layer_paths(self, prefix: str = "") -> List[str]:
        """All addressable layer names under this module (depth-first)."""
        return [prefix or self.name or type(self).__name__.lower()]

    def num_params(self, params: Params) -> int:
        import jax
        return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))


class Sequential(Module):
    """Named chain of modules; the unit of layer addressing.

    ``apply`` optionally records activations for tap names into ``taps_out`` and
    batch statistics (from BatchNorm layers in train mode) into ``stats_out``,
    keyed by layer path — the side channel the train step uses for EMA updates.
    """

    is_container = True

    def __init__(self, layers: Sequence[Tuple[str, Module]], name: str = ""):
        self.layers: List[Tuple[str, Module]] = list(layers)
        self.name = name

    def init(self, rng, in_shape):
        params: Params = {}
        keys = _rng_split(rng, max(len(self.layers), 1))
        shape = in_shape
        for (lname, layer), k in zip(self.layers, keys):
            p, shape = layer.init(k, shape)
            if p:
                params[lname] = p
        return params, shape

    def apply(self, params, x, train: bool = False,
              taps: Optional[Set[str]] = None, taps_out: Optional[Dict[str, Any]] = None,
              stats_out: Optional[Dict[str, Any]] = None, _prefix: str = ""):
        for lname, layer in self.layers:
            path = f"{_prefix}{lname}"
            p = params.get(lname, {})
            if getattr(layer, "is_container", False):
                x = layer.apply(p, x, train=train, taps=taps, taps_out=taps_out,
                                stats_out=stats_out, _prefix=path + "/")
            elif isinstance(layer, BatchNorm):
                x = layer.apply(p, x, train=train, stats_out=stats_out, _path=path)
            else:
                x = layer.apply(p, x, train=train)
            x = _constrain_activation(x)
            if taps is not None and taps_out is not None and path in taps:
                taps_out[path] = x
        return x

    def layer_paths(self, prefix: str = "") -> List[str]:
        out: List[str] = []
        for lname, layer in self.layers:
            path = f"{prefix}{lname}"
            if getattr(layer, "is_container", False):
                out.extend(layer.layer_paths(path + "/"))
            out.append(path)
        return out


class Fn(Module):
    """Stateless elementwise/shape op from a pure function."""

    def __init__(self, fn: Callable, out_shape_fn: Optional[Callable] = None):
        self.fn = fn
        self.out_shape_fn = out_shape_fn

    def init(self, rng, in_shape):
        if self.out_shape_fn is not None:
            return {}, self.out_shape_fn(in_shape)
        # abstract shape probe: traces fn without running it on any backend,
        # so ops that only work under jit (or would be wrong on host numpy)
        # still probe correctly, and value-dependent shapes fail loudly at
        # init instead of silently committing to the zero-input's shape
        import jax

        spec = jax.ShapeDtypeStruct((1,) + tuple(in_shape), np.float32)
        out = jax.eval_shape(self.fn, spec)
        return {}, tuple(out.shape[1:])

    def apply(self, params, x, train: bool = False):
        return self.fn(x)


def _relu_fn(x):
    import jax.numpy as jnp
    return jnp.maximum(x, 0)


def _identity_shape(s):
    return s


def _flatten_fn(x):
    import jax.numpy as jnp
    return jnp.reshape(x, (x.shape[0], -1))


def _flat_shape(s):
    return (int(np.prod(s)),)


# module-level fns (not lambdas) so Fn modules pickle for persistence
def relu() -> Fn:
    return Fn(_relu_fn, _identity_shape)


def flatten() -> Fn:
    return Fn(_flatten_fn, _flat_shape)


def _conv_out_dim(size: int, k: int, stride: int, pad) -> int:
    """Output spatial dim for one axis; pad is 'SAME' | 'VALID' | (lo, hi)."""
    if pad == "SAME":
        return -(-size // stride)
    if pad == "VALID":
        return (size - k) // stride + 1
    lo, hi = pad
    return (size + lo + hi - k) // stride + 1


def _axis_pads(padding, n_axes: int):
    """Normalize a padding spec to per-axis entries for _conv_out_dim."""
    if isinstance(padding, str):
        return [padding] * n_axes
    return list(padding)


class Conv2D(Module):
    """NHWC conv on the MXU: inputs/kernel in matmul_dtype() (bf16 default;
    the MXU accumulates f32 internally — preferred_element_type can't be used
    here, see the comment in apply()).

    ``padding``: "SAME" | "VALID" | explicit ((top,bottom),(left,right)) — the explicit
    form gives bit-parity with frameworks that pad symmetrically where XLA's SAME would
    split the remainder low/high differently (torch transplants, see torch_import.py).
    """

    def __init__(self, features: int, kernel: Tuple[int, int] = (3, 3),
                 strides: Tuple[int, int] = (1, 1), padding="SAME",
                 use_bias: bool = False):
        self.features = features
        self.kernel = kernel
        self.strides = strides
        self.padding = padding if isinstance(padding, str) else \
            tuple((int(a), int(b)) for a, b in padding)
        self.use_bias = use_bias

    def init(self, rng, in_shape):
        import jax
        h, w, c = in_shape
        kh, kw = self.kernel
        fan_in = kh * kw * c
        wkey, _ = _rng_split(rng, 2)
        kernel = jax.random.normal(wkey, (kh, kw, c, self.features), dtype=np.float32)
        kernel = kernel * np.float32(math.sqrt(2.0 / fan_in))
        params = {"kernel": kernel}
        if self.use_bias:
            params["bias"] = np.zeros((self.features,), dtype=np.float32)
        ph, pw = _axis_pads(self.padding, 2)
        oh = _conv_out_dim(h, kh, self.strides[0], ph)
        ow = _conv_out_dim(w, kw, self.strides[1], pw)
        return params, (oh, ow, self.features)

    def apply(self, params, x, train: bool = False):
        import jax
        import jax.numpy as jnp
        dt = getattr(jnp, matmul_dtype())
        # no preferred_element_type: the conv transpose rule requires the
        # cotangent dtype to match the inputs, so an f32-accumulate bf16 conv
        # is not differentiable; the TPU MXU accumulates f32 internally anyway
        y = jax.lax.conv_general_dilated(
            x.astype(dt),
            jnp.asarray(params["kernel"]).astype(dt),
            window_strides=self.strides,
            padding=self.padding if isinstance(self.padding, str) else list(self.padding),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # bf16 activations end-to-end: half the HBM traffic
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return y


class Dense(Module):
    def __init__(self, features: int, use_bias: bool = True):
        self.features = features
        self.use_bias = use_bias

    def init(self, rng, in_shape):
        import jax
        d = in_shape[-1]  # acts on the last dim; leading dims (e.g. time) pass through
        wkey, _ = _rng_split(rng, 2)
        w = jax.random.normal(wkey, (d, self.features), dtype=np.float32)
        w = w * np.float32(1.0 / math.sqrt(d))
        params = {"kernel": w}
        if self.use_bias:
            params["bias"] = np.zeros((self.features,), dtype=np.float32)
        return params, tuple(in_shape[:-1]) + (self.features,)

    def apply(self, params, x, train: bool = False):
        import jax.numpy as jnp
        dt = getattr(jnp, matmul_dtype())
        y = jnp.dot(x.astype(dt), jnp.asarray(params["kernel"]).astype(dt),
                    preferred_element_type=jnp.float64 if dt == jnp.float64
                    else jnp.float32)
        y = y.astype(dt if dt == jnp.float64 else jnp.float32)
        if self.use_bias:
            y = y + params["bias"]
        return y


class BatchNorm(Module):
    """Inference-style batchnorm (scale/bias/moving stats).

    Train-mode uses batch statistics; the cross-device mean/var reduction is left to
    XLA (inside pjit, reductions over the batch dim are automatically global when the
    batch is sharded — no explicit psum needed under jit-of-sharded-computation).
    """

    def __init__(self, momentum: float = 0.9, eps: float = 1e-5):
        self.momentum = momentum
        self.eps = eps

    def init(self, rng, in_shape):
        c = in_shape[-1]
        params = {
            "scale": np.ones((c,), dtype=np.float32),
            "bias": np.zeros((c,), dtype=np.float32),
            "mean": np.zeros((c,), dtype=np.float32),
            "var": np.ones((c,), dtype=np.float32),
        }
        return params, in_shape

    def apply(self, params, x, train: bool = False,
              stats_out: Optional[Dict[str, Any]] = None, _path: str = ""):
        import jax
        import jax.numpy as jnp
        if train:
            axes = tuple(range(x.ndim - 1))
            xf = x.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
            if stats_out is not None:
                # stop_gradient: stats feed EMA updates, not the loss
                stats_out[_path] = (jax.lax.stop_gradient(mean),
                                    jax.lax.stop_gradient(var))
        else:
            mean, var = params["mean"], params["var"]
        inv = params["scale"] * jnp.reciprocal(jnp.sqrt(var + self.eps))
        shift = params["bias"] - mean * inv
        return x * inv.astype(x.dtype) + shift.astype(x.dtype)


class MaxPool(Module):
    """Max pooling; ``padding`` like Conv2D ("SAME"/"VALID"/explicit per-axis pairs).
    Explicit pads fill with -inf (pure window semantics, matches torch)."""

    def __init__(self, window: Tuple[int, int] = (2, 2),
                 strides: Optional[Tuple[int, int]] = None, padding="SAME"):
        self.window = window
        self.strides = strides or window
        self.padding = padding if isinstance(padding, str) else \
            tuple((int(a), int(b)) for a, b in padding)

    def init(self, rng, in_shape):
        h, w, c = in_shape
        ph, pw = _axis_pads(self.padding, 2)
        oh = _conv_out_dim(h, self.window[0], self.strides[0], ph)
        ow = _conv_out_dim(w, self.window[1], self.strides[1], pw)
        return {}, (oh, ow, c)

    def apply(self, params, x, train: bool = False):
        import jax
        import jax.numpy as jnp
        pad = self.padding if isinstance(self.padding, str) else \
            [(0, 0)] + list(self.padding) + [(0, 0)]
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            (1,) + self.window + (1,), (1,) + self.strides + (1,), pad)


class GlobalAvgPool(Module):
    def init(self, rng, in_shape):
        return {}, (in_shape[-1],)

    def apply(self, params, x, train: bool = False):
        import jax.numpy as jnp
        return jnp.mean(x.astype(jnp.float32), axis=(1, 2))


# ---------------------------------------------------------------------------
# Residual blocks (used by resnet.py)
# ---------------------------------------------------------------------------

class Residual(Module):
    """y = act(body(x) + shortcut(x)); shortcut projects when shapes change.
    ``activation``: "relu" (ResNet convention) or None (pre-norm transformer
    blocks, where the residual stream stays linear)."""

    is_container = True

    def __init__(self, body: Sequential, shortcut: Optional[Sequential] = None,
                 activation: Optional[str] = "relu"):
        self.body = body
        self.shortcut = shortcut
        self.activation = activation

    def init(self, rng, in_shape):
        k1, k2 = _rng_split(rng, 2)
        bp, out_shape = self.body.init(k1, in_shape)
        params = {"body": bp}
        if self.shortcut is not None:
            sp, s_shape = self.shortcut.init(k2, in_shape)
            if s_shape != out_shape:
                raise ValueError(f"Residual shapes differ: {s_shape} vs {out_shape}")
            params["shortcut"] = sp
        elif in_shape != out_shape:
            raise ValueError(
                f"Residual needs a projection shortcut: {in_shape} -> {out_shape}")
        return params, out_shape

    def apply(self, params, x, train: bool = False,
              taps: Optional[Set[str]] = None, taps_out: Optional[Dict[str, Any]] = None,
              stats_out: Optional[Dict[str, Any]] = None, _prefix: str = ""):
        import jax.numpy as jnp
        y = self.body.apply(params["body"], x, train=train, taps=taps,
                            taps_out=taps_out, stats_out=stats_out,
                            _prefix=_prefix + "body/")
        s = x
        if self.shortcut is not None:
            s = self.shortcut.apply(params["shortcut"], x, train=train, taps=taps,
                                    taps_out=taps_out, stats_out=stats_out,
                                    _prefix=_prefix + "shortcut/")
        out = y + s
        if getattr(self, "activation", "relu") == "relu":
            out = jnp.maximum(out, 0)
        return _constrain_activation(out)

    def layer_paths(self, prefix: str = "") -> List[str]:
        out = self.body.layer_paths(prefix + "body/")
        if self.shortcut is not None:
            out.extend(self.shortcut.layer_paths(prefix + "shortcut/"))
        return out


# ---------------------------------------------------------------------------
# FunctionModel: the SerializableFunction-equivalent handle
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FunctionModel:
    """A (module, params) pair with named inputs/outputs — the unit DNNModel evaluates.

    Plays the role of the reference's ``SerializableFunction`` wrapper around a native
    CNTK ``Function`` (CNTK/SerializableFunction.scala:85-143): a self-contained,
    persistable model handle with addressable argument/output nodes. Serialization is
    structural (module tree pickles; params pytree saved as npz by core/serialize.py)
    instead of opaque native bytes.

    ``layer_names``: orderd list of tap paths from the classifier head backwards, used
    by ImageFeaturizer's cutOutputLayers (reference downloader/Schema.scala:44-100).
    """

    module: Module
    params: Params
    input_shape: Tuple[int, ...]
    layer_names: List[str] = dataclasses.field(default_factory=list)
    name: str = "model"
    # image-input layout: native modules are NHWC; ONNX imports are NCHW.
    # Consumers (ImageFeaturizer) read this to orient the pixel array.
    data_format: str = "NHWC"

    def cache_token(self) -> str:
        """Stable cross-process identity of the traced computation, for
        compile-cache keys (DeviceFn.key). Params are ARGUMENTS to the
        compiled forward, so the token binds the architecture (the pickled
        module tree — the same structural-serialization contract
        core/serialize.py relies on) plus the param tree's layout
        (treedef, leaf shapes, dtypes) — NOT weight values. Two processes
        loading the same model therefore agree on the token, which is what
        lets the fleet's persistent compile cache (serving/fleet/cache.py)
        hand a fresh replica an executable compiled elsewhere. Falls back
        to the process-local ``id()`` when the module tree won't pickle
        (opaque native handles) — correctness keeps, cross-process reuse
        degrades."""
        tok = getattr(self, "_cache_token", None)
        if tok is None:
            import hashlib
            import pickle

            import jax
            try:
                leaves, treedef = jax.tree.flatten(self.params)
                spec = tuple(
                    (tuple(int(d) for d in np.shape(leaf)),
                     str(getattr(leaf, "dtype", type(leaf).__name__)))
                    for leaf in leaves)
                blob = pickle.dumps(
                    (self.module, tuple(self.input_shape),
                     tuple(self.layer_names), self.name, self.data_format,
                     str(treedef), spec), protocol=4)
                tok = "m:" + hashlib.sha256(blob).hexdigest()[:20]
            except Exception:  # noqa: BLE001 — unpicklable module tree
                tok = f"id:{id(self)}"
            self._cache_token = tok
        return tok

    def argument_names(self) -> List[str]:
        """Graph input names (multi-input GraphModules list all of them)."""
        names = getattr(self.module, "input_names", None)
        return list(names) if names else ["ARGUMENT_0"]

    def resolve_input(self, node: str) -> str:
        """Resolve an input spec (``ARGUMENT_i`` or a raw graph input name)
        to the module's input tensor name. (Reference:
        SerializableFunction.scala:61-63 ARGUMENT_i addressing.)"""
        names = self.argument_names()
        if node.startswith("ARGUMENT_"):
            suffix = node[len("ARGUMENT_"):]
            if not suffix.isdigit() or int(suffix) >= len(names):
                raise KeyError(
                    f"{node!r}: model has {len(names)} argument(s) ({names}); "
                    f"valid indices are 0..{len(names) - 1}")
            return names[int(suffix)]
        if node in names:
            return node
        raise KeyError(f"Unknown input node {node!r}; known: {names} "
                       f"or ARGUMENT_i")

    def output_names(self) -> List[str]:
        return ["OUTPUT_0"] + list(self.layer_names)

    def resolve_output(self, node: Optional[str]) -> Optional[str]:
        """Resolve a fetch-node spec to a tap path (None = final output).

        Accepts a layer path, ``OUTPUT_i`` positional addressing, or None.
        (Reference: SerializableFunction.scala:61-63,115-129.)
        """
        if node is None or node == "OUTPUT_0" or node == self.name:
            return None
        if node.startswith("OUTPUT_"):
            i = int(node.split("_", 1)[1])
            return self.layer_names[i - 1] if i > 0 else None
        paths = set(self.module.layer_paths())
        if node in paths:
            return node
        raise KeyError(f"Unknown output node {node!r}; known: OUTPUT_i, {sorted(paths)[:20]}...")

    def apply(self, x, tap: Optional[str] = None, train: bool = False):
        """Forward pass; if ``tap`` is a layer path, return that activation instead."""
        if tap is None:
            return self.module.apply(self.params, x, train=train)
        taps_out: Dict[str, Any] = {}
        if not getattr(self.module, "is_container", False):
            raise ValueError(
                "taps need a container root (Sequential/GraphModule)")
        self.module.apply(self.params, x, train=train, taps={tap}, taps_out=taps_out)
        if tap not in taps_out:
            raise KeyError(f"Tap {tap!r} not produced; known {self.module.layer_paths()[:20]}")
        return taps_out[tap]

    def apply_taps(self, x, taps, train: bool = False):
        """ONE forward pass fetching several nodes (fetchDict parity —
        cntk/CNTKModel.scala:204-223 evaluates all fetch variables in a
        single native eval). ``taps`` is a list of tap paths where ``None``
        means the final output; returns {tap: activation}."""
        real = {t for t in taps if t is not None}
        taps_out: Dict[str, Any] = {}
        if real:
            if not getattr(self.module, "is_container", False):
                raise ValueError(
                    "taps need a container root (Sequential/GraphModule)")
            out = self.module.apply(self.params, x, train=train, taps=real,
                                    taps_out=taps_out)
        else:
            out = self.module.apply(self.params, x, train=train)
        missing = real - set(taps_out)
        if missing:
            raise KeyError(f"Taps {sorted(missing)} not produced; known "
                           f"{self.module.layer_paths()[:20]}")
        result = dict(taps_out)
        if None in list(taps):
            result[None] = out
        return result
