"""Training step: loss, optimizer wiring, mesh-sharded train step.

The reference trains DNNs outside the framework (CNTK models arrive pretrained via
ModelDownloader) and trains heads with LightGBM/VW. The TPU build makes DNN training
first-class because transfer learning *is* the north-star benchmark (BASELINE.md):
a jitted, mesh-sharded train step over (data, fsdp, tensor) axes, scaling-book style
— annotate shardings, let XLA insert the collectives.

  - batch sharded over ("data", "fsdp")    — DP; fsdp axis also feeds batch so FSDP
    all-gathers amortize (standard ZeRO-3 layout).
  - conv kernels sharded cin->fsdp, cout->tensor; dense din->fsdp, dout->tensor.
    Dims not divisible by the axis stay replicated (mesh-agnostic degradation).
  - bf16 activations/matmuls (module layer property), f32 params + optimizer state.
"""

from __future__ import annotations

import dataclasses
import functools
import signal as _signal
import threading
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from .module import Module, Sequential
from ..core import faults
from ..parallel.mesh import DATA_AXIS, FSDP_AXIS, TENSOR_AXIS


def cross_entropy_loss(logits, labels, mask=None):
    """Mean softmax cross-entropy; labels are int class ids over the leading
    dims. Handles [B, K] logits with [B] labels AND per-token [B, T, K] with
    [B, T] (sequence taggers/LMs) — classes are always the last axis. Padded
    rows/tokens masked out via ``mask`` of the labels' shape."""
    import jax
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(
        logits, labels[..., None].astype(jnp.int32), axis=-1)[..., 0] - lse
    if mask is not None:
        m = mask.astype(jnp.float32)
        return -(ll * m).sum() / jnp.maximum(m.sum(), 1.0)
    return -ll.mean()


def accuracy(logits, labels, mask=None):
    import jax.numpy as jnp

    pred = jnp.argmax(logits, axis=-1)
    hit = (pred == labels).astype(jnp.float32)
    if mask is not None:
        m = mask.astype(jnp.float32)
        return (hit * m).sum() / jnp.maximum(m.sum(), 1.0)
    return hit.mean()


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: Any


def _register_train_state():
    import jax

    jax.tree_util.register_dataclass(
        TrainState, data_fields=["params", "opt_state", "step"], meta_fields=[])


_register_train_state()


def _decay_mask(params):
    """Weight decay only touches matmul/conv kernels — never biases, BN scale/shift,
    or BN moving statistics (decaying `var` toward 0 explodes 1/sqrt(var+eps))."""
    import jax

    return jax.tree.map(lambda leaf: np.ndim(leaf) >= 2, params)


def make_optimizer(learning_rate: float = 0.1, momentum: float = 0.9,
                   weight_decay: float = 0.0):
    import optax

    txs = []
    if weight_decay:
        txs.append(optax.add_decayed_weights(weight_decay, mask=_decay_mask))
    txs.append(optax.sgd(learning_rate, momentum=momentum))
    return optax.chain(*txs)


def _apply_bn_ema(params, stats: Dict[str, Any], momentum: float):
    """Fold batch statistics into the BatchNorm moving mean/var params.

    ``stats`` is keyed by layer path ("stem/bn", "layer1/0/body/bn1", ...); each
    path addresses a nested params dict holding {"mean", "var"}.
    """
    for path, (mean, var) in stats.items():
        node = params
        keys = path.split("/")
        for k in keys[:-1]:
            node = node[k]
        bn = dict(node[keys[-1]])
        bn["mean"] = momentum * bn["mean"] + (1 - momentum) * mean
        bn["var"] = momentum * bn["var"] + (1 - momentum) * var
        node[keys[-1]] = bn
    return params


def make_train_step(module: Module, optimizer, bn_momentum: float = 0.9) -> Callable:
    """Pure (state, batch) -> (state, metrics) step; jit/pjit-ready.

    BatchNorm layers use batch statistics in the forward pass and their moving
    mean/var params are EMA-updated from the same statistics (side-channel via
    ``stats_out``), so eval-mode inference after training is correct.
    """

    def step(state: TrainState, batch: Dict[str, Any]) -> Tuple[TrainState, Dict]:
        import jax
        import optax

        x, y = batch["x"], batch["y"]
        mask = batch.get("mask")

        def loss_fn(params):
            stats: Dict[str, Any] = {}
            if isinstance(module, Sequential):
                logits = module.apply(params, x, train=True, stats_out=stats)
            else:
                logits = module.apply(params, x, train=True)
            return cross_entropy_loss(logits, y, mask), (logits, stats)

        (loss, (logits, stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        if stats:
            params = _apply_bn_ema(jax.tree.map(lambda v: v, params), stats, bn_momentum)
        metrics = {"loss": loss, "accuracy": accuracy(logits, y, mask)}
        return TrainState(params, opt_state, state.step + 1), metrics

    return step


class PreemptionGuard:
    """Turns a preemption signal (SIGTERM — what TPU VMs get on maintenance
    events and spot reclaims) into a flag the training loop polls between
    steps, so the loop checkpoints and exits cleanly instead of dying
    mid-step.

    ``request()`` triggers the same path programmatically (tests, cluster
    agents that learn of preemption out-of-band). Installing the handler only
    works on the main thread; elsewhere the guard silently degrades to the
    programmatic path.
    """

    def __init__(self, signals: Tuple[int, ...] = (_signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prev: Dict[int, Any] = {}

    def request(self) -> None:
        self._event.set()

    def requested(self) -> bool:
        return self._event.is_set()

    def __enter__(self) -> "PreemptionGuard":
        for sig in self.signals:
            try:
                self._prev[sig] = _signal.signal(
                    sig, lambda *_: self._event.set())
            except ValueError:  # not the main thread
                pass
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._prev.items():
            try:
                _signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev.clear()


@dataclasses.dataclass
class TrainLoopResult:
    state: TrainState
    steps_run: int
    preempted: bool
    last_metrics: Optional[Dict[str, float]]


def _batch_rows(batch) -> Optional[int]:
    """Leading-dim row count of a step batch (tuple/dict/array pytrees);
    None when nothing array-like is found."""
    if isinstance(batch, (tuple, list)) and batch:
        return _batch_rows(batch[0])
    if isinstance(batch, dict) and batch:
        return _batch_rows(next(iter(batch.values())))
    shape = getattr(batch, "shape", None)
    if shape:
        return int(shape[0])
    return None


def run_train_loop(state: TrainState, step_fn: Callable, batches: Iterable,
                   *, checkpoint_path: Optional[str] = None,
                   every_k: int = 100,
                   guard: Optional[PreemptionGuard] = None,
                   resume: bool = True,
                   log: Optional[Callable[[str], None]] = None,
                   registry=None) -> TrainLoopResult:
    """Drive ``step_fn`` over ``batches`` with checkpoint/resume and a
    preemption hook — the DNN counterpart of the GBDT checkpointed fit.

    ``checkpoint_path``: TrainState saved there every ``every_k`` steps and
    on preemption (models.checkpoint/orbax — sharded arrays restore onto
    their original device placement via the live ``state`` as reference).
    ``resume=True`` restores it when present and skips the already-trained
    prefix of ``batches`` by the restored step counter — a deterministic
    (seeded/indexed) batch stream therefore replays the exact uninterrupted
    schedule. ``guard``: a PreemptionGuard polled between steps; when it
    fires, the loop checkpoints once more and returns ``preempted=True``.

    ``registry``: obs MetricsRegistry receiving the per-step series
    (``mmlspark_train_*{engine="dnn"}``: step time, examples/s, loss,
    checkpoint latency); defaults to the process-wide registry so
    ``/_mmlspark/metrics`` scrapes see training progress.
    """
    import time as _time

    from .checkpoint import load_train_state, save_train_state
    from ..obs.metrics import TrainRecorder

    recorder = TrainRecorder("dnn", registry=registry)

    def _save_timed(st):
        t0 = _time.perf_counter()
        save_train_state(st, checkpoint_path)
        recorder.checkpoint(_time.perf_counter() - t0)

    start_step = 0
    if checkpoint_path is not None and resume:
        import os

        if os.path.exists(checkpoint_path):
            state = load_train_state(checkpoint_path, like=state)
            start_step = int(np.asarray(state.step))
            if log:
                log(f"resumed from {checkpoint_path} at step {start_step}")

    steps_run = 0
    metrics_out: Optional[Dict[str, float]] = None
    dirty = False  # steps since the last save
    preempted = False
    for i, batch in enumerate(batches):
        if i < start_step:
            continue  # replayed prefix: already folded into the checkpoint
        if guard is not None and guard.requested():
            preempted = True
            break
        faults.fire(faults.TRAIN_STEP, step=i, engine="dnn")
        t_step = _time.perf_counter()
        state, metrics = step_fn(state, batch)
        dur = _time.perf_counter() - t_step
        steps_run += 1
        dirty = True
        metrics_out = metrics
        recorder.step(dur, examples=_batch_rows(batch),
                      loss=(metrics or {}).get("loss"))
        if checkpoint_path is not None and steps_run % max(every_k, 1) == 0:
            _save_timed(state)
            dirty = False
    else:
        if guard is not None and guard.requested():
            preempted = True
    if checkpoint_path is not None and (dirty or preempted):
        _save_timed(state)
    if metrics_out is not None:
        metrics_out = {k: float(v) for k, v in metrics_out.items()}
    return TrainLoopResult(state=state, steps_run=steps_run,
                           preempted=preempted, last_metrics=metrics_out)


def param_sharding_rules(params, mesh):
    """NamedSharding tree: cin->fsdp, cout->tensor for matmul/conv kernels,
    replicate the rest; any non-divisible dim falls back to replicated."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    fsdp = mesh.shape.get(FSDP_AXIS, 1)
    tens = mesh.shape.get(TENSOR_AXIS, 1)

    def rule(leaf):
        shape = leaf.shape
        if len(shape) == 4:  # conv kernel [kh,kw,cin,cout]
            spec = [None, None,
                    FSDP_AXIS if fsdp > 1 and shape[2] % fsdp == 0 else None,
                    TENSOR_AXIS if tens > 1 and shape[3] % tens == 0 else None]
            return NamedSharding(mesh, P(*spec))
        if len(shape) == 2:  # dense kernel [din,dout]
            spec = [FSDP_AXIS if fsdp > 1 and shape[0] % fsdp == 0 else None,
                    TENSOR_AXIS if tens > 1 and shape[1] % tens == 0 else None]
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(rule, params)


def batch_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P((DATA_AXIS, FSDP_AXIS)))


def init_train_state(module: Module, in_shape, optimizer, seed: int = 0,
                     mesh=None) -> TrainState:
    """Initialize params (+opt state); if a mesh is given, place both sharded."""
    import jax

    params, _ = module.init(jax.random.PRNGKey(seed), in_shape)
    if mesh is not None:
        shardings = param_sharding_rules(params, mesh)
        params = jax.device_put(params, shardings)
    opt_state = optimizer.init(params)
    step = np.int32(0)
    return TrainState(params, opt_state, step)


def compile_train_step(module: Module, optimizer, mesh=None):
    """jit the train step. Sharding comes from the *inputs* (GSPMD propagation):
    place state via init_train_state(mesh=...) and batches via batch_sharding(mesh);
    XLA inserts the DP gradient psums / FSDP all-gathers / TP collectives.

    Pass ``mesh`` when training over a multi-device mesh: activations are then
    anchored to the batch sharding via module.activation_sharding — without
    the anchors the XLA SPMD partitioners (Shardy and GSPMD alike) produce
    WRONG conv gradients for channel-sharded kernels at small spatial sizes
    (see activation_sharding's docstring; the equivalence test in
    tests/test_models.py fails by ~1e-1 without this)."""
    import jax

    from .module import activation_sharding

    step = make_train_step(module, optimizer)
    if mesh is None:
        return jax.jit(step, donate_argnums=(0,))

    constraint = batch_sharding(mesh)

    def step_anchored(state, batch):
        with activation_sharding(constraint):  # trace-time context
            return step(state, batch)

    return jax.jit(step_anchored, donate_argnums=(0,))
