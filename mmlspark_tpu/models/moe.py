"""Mixture-of-Experts FFN with expert parallelism over the ``expert`` axis.

No reference counterpart (MMLSpark predates MoE); this is the expert-
parallel leg of the framework's parallelism story (dp/fsdp/tp/sp/ep/pp).
Design follows the standard switch-transformer dispatch expressed as dense
einsums so GSPMD shards it (scaling-book style — annotate, let XLA insert
the all_to_alls):

  - router: tokens [B, T, D] -> logits [B, T, E], top-1 expert per token;
  - dispatch: one-hot [B, T, E, C] capacity mask (first C tokens per expert
    keep their slot, overflow drops — switch semantics), contracted against
    tokens to form per-expert buffers [E, B, C, D];
  - expert FFN: per-expert weights W1 [E, D, H], W2 [E, H, D] applied with a
    batched einsum (leading E dim shards over ``expert`` — with the buffers
    sharded the same way, XLA inserts the dispatch/return all_to_all);
  - combine: the same mask scatters expert outputs back to token positions,
    scaled by the router probability.

``expert_shardings(mesh)`` gives the NamedShardings to place params/buffers;
the equality test (sharded == single-device) runs on an 8-device mesh.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from .module import Module, _rng_split, matmul_dtype


class MoE(Module):
    """Top-1 (switch) MoE FFN on [T, D] rows (batch dim added at apply)."""

    def __init__(self, num_experts: int, hidden: Optional[int] = None,
                 capacity_factor: float = 1.5):
        self.num_experts = num_experts
        self.hidden = hidden
        self.capacity_factor = capacity_factor

    def init(self, rng, in_shape):
        import jax

        t, d = in_shape
        h = self.hidden or 4 * d
        kr, k1, k2 = _rng_split(rng, 3)
        e = self.num_experts
        return {
            "router": jax.random.normal(kr, (d, e), dtype=np.float32)
            * np.float32(1.0 / math.sqrt(d)),
            "w1": jax.random.normal(k1, (e, d, h), dtype=np.float32)
            * np.float32(1.0 / math.sqrt(d)),
            "w2": jax.random.normal(k2, (e, h, d), dtype=np.float32)
            * np.float32(1.0 / math.sqrt(h)),
        }, (t, d)

    def _capacity(self, tokens: int) -> int:
        return max(1, int(math.ceil(
            tokens * self.capacity_factor / self.num_experts)))

    def apply(self, params, x, train: bool = False):
        import jax
        import jax.numpy as jnp

        B, T, D = x.shape
        E = self.num_experts
        C = self._capacity(T)
        dt = getattr(jnp, matmul_dtype())

        logits = jnp.einsum("btd,de->bte", x.astype(jnp.float32),
                            jnp.asarray(params["router"]))
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)                  # [B, T]
        gate = jnp.max(probs, axis=-1)                       # [B, T]
        onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # [B, T, E]
        # position of each token within its expert's buffer; >=C overflows drop
        pos = jnp.cumsum(onehot, axis=1) * onehot - 1.0      # [B, T, E]
        keep = (pos >= 0) & (pos < C)
        dispatch = jax.nn.one_hot(pos.astype(jnp.int32), C,
                                  dtype=jnp.float32) * keep[..., None]
        # [B, T, E, C] x [B, T, D] -> expert buffers [E, B, C, D]
        buf = jnp.einsum("btec,btd->ebcd", dispatch, x.astype(jnp.float32))
        w1 = jnp.asarray(params["w1"]).astype(dt)
        w2 = jnp.asarray(params["w2"]).astype(dt)
        hmid = jax.nn.relu(jnp.einsum("ebcd,edh->ebch", buf.astype(dt), w1,
                                      preferred_element_type=jnp.float32))
        out_buf = jnp.einsum("ebch,ehd->ebcd", hmid.astype(dt), w2,
                             preferred_element_type=jnp.float32)
        # combine back to token positions, gate-scaled
        combined = jnp.einsum("btec,ebcd->btd", dispatch,
                              out_buf.astype(jnp.float32))
        return combined * gate[..., None]


def expert_shardings(mesh, params):
    """Shardings pytree mirroring ``params``: expert-indexed leaves (w1/w2)
    shard their leading E dim over the 'expert' axis; the router replicates.
    Pass straight to ``jax.device_put(params, expert_shardings(mesh, params))``."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("w1", "w2"):
            return NamedSharding(mesh, P("expert"))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(place, params)
