"""Import torchvision-style ResNet checkpoints into native NHWC FunctionModels.

The reference's transfer-learning story starts from *real pretrained* backbones pulled
by ModelDownloader (downloader/ModelDownloader.scala:27-120); this module is the direct
path for the dominant pretrained-weight ecosystem: a torchvision `resnetXX`
``state_dict`` (an ImageNet checkpoint .pth) becomes our native ResNet — NHWC, bf16
MXU convs, name-addressable layers — with exact numerics (explicit torch-style padding,
see resnet._pad).

Accepts a state_dict mapping or a .pth path (torch.load on CPU; torch is an allowed
host-side dependency — it never touches the TPU compute path).
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .module import FunctionModel
from .resnet import _CONFIGS, build_resnet


def _to_np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    return t.detach().cpu().numpy()  # torch tensor


def _conv(sd: Dict, key: str) -> Dict[str, np.ndarray]:
    # torch OIHW -> our HWIO
    p = {"kernel": np.transpose(_to_np(sd[key + ".weight"]), (2, 3, 1, 0))
         .astype(np.float32)}
    if key + ".bias" in sd:
        p["bias"] = _to_np(sd[key + ".bias"]).astype(np.float32)
    return p


def _bn(sd: Dict, key: str) -> Dict[str, np.ndarray]:
    return {
        "scale": _to_np(sd[key + ".weight"]).astype(np.float32),
        "bias": _to_np(sd[key + ".bias"]).astype(np.float32),
        "mean": _to_np(sd[key + ".running_mean"]).astype(np.float32),
        "var": _to_np(sd[key + ".running_var"]).astype(np.float32),
    }


def from_torch_resnet(state_dict, depth: int = 50, num_classes: int = None,
                      image_size: int = 224) -> FunctionModel:
    """Map a torchvision resnet{18,34,50,101,152} state_dict onto a native FunctionModel.

    num_classes defaults to the checkpoint's own head width (fc.weight rows)."""
    if isinstance(state_dict, (str, bytes)):
        import torch

        state_dict = torch.load(state_dict, map_location="cpu", weights_only=True)
    if hasattr(state_dict, "state_dict"):  # a whole nn.Module
        state_dict = state_dict.state_dict()
    sd = dict(state_dict)
    if num_classes is None:
        num_classes = int(_to_np(sd["fc.weight"]).shape[0])

    kind, blocks = _CONFIGS[depth]
    module = build_resnet(depth, num_classes=num_classes, image_size=image_size,
                          torch_padding=True)

    params: Dict = {
        "stem": {"conv": _conv(sd, "conv1"), "bn": _bn(sd, "bn1")},
    }
    n_body_convs = 3 if kind == "bottleneck" else 2
    for i, n in enumerate(blocks):
        stage: Dict = {}
        for j in range(n):
            tk = f"layer{i + 1}.{j}"
            body: Dict = {}
            for c in range(1, n_body_convs + 1):
                body[f"conv{c}"] = _conv(sd, f"{tk}.conv{c}")
                body[f"bn{c}"] = _bn(sd, f"{tk}.bn{c}")
            block: Dict = {"body": body}
            if f"{tk}.downsample.0.weight" in sd:
                block["shortcut"] = {"conv": _conv(sd, f"{tk}.downsample.0"),
                                     "bn": _bn(sd, f"{tk}.downsample.1")}
            stage[str(j)] = block
        params[f"layer{i + 1}"] = stage

    fc_w = _to_np(sd["fc.weight"]).astype(np.float32)  # (out, in) -> (in, out)
    params["fc"] = {"kernel": fc_w.T.copy(), "bias": _to_np(sd["fc.bias"]).astype(np.float32)}

    # shape-check the transplant against the module's own init structure
    import jax

    ref_params, out_shape = module.init(jax.random.PRNGKey(0),
                                        (image_size, image_size, 3))
    ref_shapes = jax.tree.map(lambda a: a.shape, ref_params)
    got_shapes = jax.tree.map(lambda a: a.shape, params)
    if ref_shapes != got_shapes:
        raise ValueError(
            "state_dict structure does not match resnet"
            f"{depth}: expected {ref_shapes}\ngot {got_shapes}")
    if out_shape != (num_classes,):
        raise ValueError(f"head mismatch: {out_shape} vs num_classes={num_classes}")

    layer_names = ["fc", "avgpool", "layer4", "layer3", "layer2", "layer1", "stem"]
    return FunctionModel(module=module, params=params,
                         input_shape=(image_size, image_size, 3),
                         layer_names=layer_names, name=f"resnet{depth}")
