"""TrainClassifier / TrainRegressor: auto-featurizing estimator wrappers.

Reference: train/TrainClassifier.scala:23-170 + train/TrainRegressor.scala —
wrap any estimator: reindex labels (classification), auto-featurize all
non-label columns into one vector, fit the inner estimator, and return a model
that scores with standardized column names (scored_labels / scores /
scored_probabilities) and can map predicted indexes back to original labels.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasFeaturesCol, HasLabelCol, Param
from ..core.pipeline import Estimator, Model
from ..core.schema import Schema
from ..featurize.assemble import AssembleFeatures
from ..featurize.indexers import ValueIndexer


class _TrainBase(Estimator, HasLabelCol, HasFeaturesCol):
    model = ComplexParam("model", "The inner estimator to train")
    numFeatures = Param("numFeatures", "Hash buckets for featurization", 0, ptype=int)

    def set_model(self, estimator) -> "_TrainBase":
        return self.set("model", estimator)

    def _featurize(self, df: DataFrame, label_col: str):
        feature_cols = [c for c in df.columns if c != label_col]
        if (len(feature_cols) == 1
                and df.schema[feature_cols[0]] in ("vector", "tensor")):
            # already a single vector column: pass through
            return None, feature_cols[0]
        assembler = AssembleFeatures(inputCols=feature_cols,
                                     outputCol=self.get("featuresCol"))
        if self.get("numFeatures"):
            assembler.set("numberOfFeatures", self.get("numFeatures"))
        fitted = assembler.fit(df)
        return fitted, self.get("featuresCol")


class TrainClassifier(_TrainBase):
    """Auto-featurize + label-reindex + fit a classifier
    (train/TrainClassifier.scala:23-170)."""

    reindexLabel = Param("reindexLabel", "Reindex labels to 0..K-1", True, ptype=bool)

    def fit(self, df: DataFrame) -> "TrainedClassifierModel":
        label_col = self.get_or_throw("labelCol")
        inner = self.get_or_throw("model")

        levels = None
        working = df
        if self.get("reindexLabel"):
            indexer = ValueIndexer(inputCol=label_col, outputCol=label_col).fit(df)
            levels = list(indexer.get("levels"))
            working = indexer.transform(df)

        featurizer, feat_col = self._featurize(working, label_col)
        if featurizer is not None:
            working = featurizer.transform(working)

        est = inner.copy()
        if est.has_param("featuresCol"):
            est.set("featuresCol", feat_col)
        if est.has_param("labelCol"):
            est.set("labelCol", label_col)
        fitted = est.fit(working)
        return TrainedClassifierModel(
            model=fitted, featurizer=featurizer, labelCol=label_col,
            featuresCol=feat_col, levels=levels)


class TrainedClassifierModel(Model, HasLabelCol, HasFeaturesCol):
    model = ComplexParam("model", "Fitted inner model")
    featurizer = ComplexParam("featurizer", "Fitted feature assembler (or None)")
    levels = ComplexParam("levels", "Original label values by index")

    def transform(self, df: DataFrame) -> DataFrame:
        featurizer = self.get("featurizer")
        working = featurizer.transform(df) if featurizer is not None else df
        inner = self.get_or_throw("model")
        scored = inner.transform(working)

        # standardize column names (reference SparkSchema.setLabelColumnName etc.)
        renames = {}
        if inner.has_param("predictionCol"):
            renames[inner.get("predictionCol")] = "scored_labels"
        if inner.has_param("rawPredictionCol") and \
                inner.get("rawPredictionCol") in scored.schema:
            renames[inner.get("rawPredictionCol")] = "scores"
        if inner.has_param("probabilityCol") and \
                inner.get("probabilityCol") in scored.schema:
            renames[inner.get("probabilityCol")] = "scored_probabilities"
        for old, new in renames.items():
            if old in scored.schema and old != new:
                scored = scored.with_column_renamed(old, new)

        levels = self.get("levels")
        if levels:
            def back(p):
                out = np.empty(len(p["scored_labels"]), dtype=object)
                for i, v in enumerate(p["scored_labels"]):
                    iv = int(v)
                    out[i] = levels[iv] if 0 <= iv < len(levels) else None
                return out
            scored = scored.with_column("scored_labels_original", back)
        return scored


class TrainRegressor(_TrainBase):
    """Auto-featurize + fit a regressor (train/TrainRegressor.scala)."""

    def fit(self, df: DataFrame) -> "TrainedRegressorModel":
        label_col = self.get_or_throw("labelCol")
        inner = self.get_or_throw("model")
        featurizer, feat_col = self._featurize(df, label_col)
        working = featurizer.transform(df) if featurizer is not None else df
        est = inner.copy()
        if est.has_param("featuresCol"):
            est.set("featuresCol", feat_col)
        if est.has_param("labelCol"):
            est.set("labelCol", label_col)
        fitted = est.fit(working)
        return TrainedRegressorModel(model=fitted, featurizer=featurizer,
                                     labelCol=label_col, featuresCol=feat_col)


class TrainedRegressorModel(Model, HasLabelCol, HasFeaturesCol):
    model = ComplexParam("model", "Fitted inner model")
    featurizer = ComplexParam("featurizer", "Fitted feature assembler (or None)")

    def transform(self, df: DataFrame) -> DataFrame:
        featurizer = self.get("featurizer")
        working = featurizer.transform(df) if featurizer is not None else df
        inner = self.get_or_throw("model")
        scored = inner.transform(working)
        if inner.has_param("predictionCol"):
            pc = inner.get("predictionCol")
            if pc in scored.schema and pc != "scored_labels":
                scored = scored.with_column_renamed(pc, "scored_labels")
        return scored
