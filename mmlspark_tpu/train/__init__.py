"""Training convenience layer (reference train/ package, SURVEY §2.4).

TrainClassifier/TrainRegressor auto-featurize and fit any estimator;
ComputeModelStatistics / ComputePerInstanceStatistics produce metric DataFrames.
"""

from .stages import (
    TrainClassifier,
    TrainRegressor,
    TrainedClassifierModel,
    TrainedRegressorModel,
)
from .metrics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
    MetricsLogger,
)

__all__ = [
    "ComputeModelStatistics", "ComputePerInstanceStatistics", "MetricsLogger",
    "TrainClassifier", "TrainRegressor", "TrainedClassifierModel",
    "TrainedRegressorModel",
]
