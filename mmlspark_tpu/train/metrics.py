"""Model-evaluation metric stages.

Reference: train/ComputeModelStatistics.scala:56-460 — classification metrics
(accuracy/precision/recall/AUC + confusion matrix, macro-averaged for
multiclass) and regression metrics (MSE/RMSE/R^2/MAE) as a metrics DataFrame;
train/ComputePerInstanceStatistics.scala — per-row loss columns;
MetricsLogger (:461-470) pushes metrics into the logging system.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import (
    HasEvaluationMetric,
    HasLabelCol,
    HasScoredLabelsCol,
    HasScoredProbabilitiesCol,
    HasScoresCol,
    Param,
)
from ..core.pipeline import Transformer

log = logging.getLogger("mmlspark_tpu.metrics")


def confusion_matrix(y_true: np.ndarray, y_pred: np.ndarray, k: int) -> np.ndarray:
    cm = np.zeros((k, k), dtype=np.int64)
    np.add.at(cm, (y_true.astype(np.int64), y_pred.astype(np.int64)), 1)
    return cm


def auc_score(labels: np.ndarray, scores: np.ndarray) -> float:
    order = np.argsort(scores)
    ranks = np.empty(len(scores), dtype=np.float64)
    ranks[order] = np.arange(1, len(scores) + 1)
    for v in np.unique(scores):
        m = scores == v
        if m.sum() > 1:
            ranks[m] = ranks[m].mean()
    pos = labels > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def classification_metrics(y_true: np.ndarray, y_pred: np.ndarray,
                           scores: Optional[np.ndarray] = None) -> Dict[str, Any]:
    """Binary/multiclass metrics with the reference's macro-averaging
    (ComputeModelStatistics.scala:321-365)."""
    classes = np.unique(np.concatenate([y_true, y_pred]))
    k = int(classes.max()) + 1 if len(classes) else 1
    cm = confusion_matrix(y_true, y_pred, k)
    total = cm.sum()
    accuracy = float(np.trace(cm)) / total if total else 0.0
    per_class_prec = []
    per_class_rec = []
    for c in range(k):
        tp = cm[c, c]
        fp = cm[:, c].sum() - tp
        fn = cm[c, :].sum() - tp
        per_class_prec.append(tp / (tp + fp) if tp + fp else 0.0)
        per_class_rec.append(tp / (tp + fn) if tp + fn else 0.0)
    out = {
        "confusion_matrix": cm,
        "accuracy": accuracy,
        "precision": float(np.mean(per_class_prec)),
        "recall": float(np.mean(per_class_rec)),
    }
    if k <= 2:
        # binary: positive-class precision/recall (reference behavior)
        out["precision"] = float(per_class_prec[-1])
        out["recall"] = float(per_class_rec[-1])
        if scores is not None:
            out["AUC"] = auc_score(y_true, scores)
    return out


def regression_metrics(y_true: np.ndarray, y_pred: np.ndarray) -> Dict[str, float]:
    err = y_pred - y_true
    mse = float(np.mean(err ** 2))
    var = float(np.var(y_true))
    return {
        "mean_squared_error": mse,
        "root_mean_squared_error": float(np.sqrt(mse)),
        "R^2": 1.0 - mse / var if var > 0 else 0.0,
        "mean_absolute_error": float(np.mean(np.abs(err))),
    }


class ComputeModelStatistics(Transformer, HasLabelCol, HasScoredLabelsCol,
                             HasScoresCol, HasScoredProbabilitiesCol,
                             HasEvaluationMetric):
    """Scored DataFrame -> one-row metrics DataFrame."""

    def transform(self, df: DataFrame) -> DataFrame:
        data = df.collect()
        y = np.asarray(data[self.get_or_throw("labelCol")], dtype=np.float64)
        metric = self.get("evaluationMetric") or "all"

        is_classification = metric in ("classification", "all") and \
            self.get("scoredLabelsCol") in df.schema
        if metric in ("accuracy", "precision", "recall", "AUC"):
            is_classification = True

        if is_classification:
            pred = np.asarray(data[self.get("scoredLabelsCol")], dtype=np.float64)
            scores = None
            if self.get("scoresCol") in df.schema:
                raw = data[self.get("scoresCol")]
                scores = np.array([float(np.asarray(v).reshape(-1)[-1])
                                   if v is not None else 0.0 for v in raw])
            elif self.get("scoredProbabilitiesCol") in df.schema:
                raw = data[self.get("scoredProbabilitiesCol")]
                scores = np.array([float(np.asarray(v).reshape(-1)[-1])
                                   if v is not None else 0.0 for v in raw])
            m = classification_metrics(y, pred, scores)
            row = {k: (v if not isinstance(v, np.ndarray) else v)
                   for k, v in m.items()}
            if metric in ("accuracy", "precision", "recall", "AUC"):
                row = {"confusion_matrix": m["confusion_matrix"],
                       metric: m[metric]}
            MetricsLogger.log_metrics({k: v for k, v in row.items()
                                       if not isinstance(v, np.ndarray)})
            return DataFrame.from_rows([row])

        pred_col = (self.get("scoredLabelsCol")
                    if self.get("scoredLabelsCol") in df.schema else "prediction")
        pred = np.asarray(data[pred_col], dtype=np.float64)
        m = regression_metrics(y, pred)
        if metric in m:
            m = {metric: m[metric]}
        MetricsLogger.log_metrics(m)
        return DataFrame.from_rows([m])


class ComputePerInstanceStatistics(Transformer, HasLabelCol, HasScoredLabelsCol,
                                   HasScoresCol, HasScoredProbabilitiesCol,
                                   HasEvaluationMetric):
    """Append per-row loss columns (train/ComputePerInstanceStatistics.scala)."""

    def transform(self, df: DataFrame) -> DataFrame:
        label_col = self.get_or_throw("labelCol")
        if self.get("scoredProbabilitiesCol") in df.schema:
            prob_col = self.get("scoredProbabilitiesCol")

            def fn(p):
                n = len(p[label_col])
                out = np.empty(n, dtype=np.float64)
                for i in range(n):
                    y = int(p[label_col][i])
                    probs = np.asarray(p[prob_col][i], dtype=np.float64).reshape(-1)
                    pi = probs[y] if 0 <= y < len(probs) else 1e-15
                    out[i] = -np.log(max(pi, 1e-15))
                return out

            return df.with_column("log_loss", fn)

        pred_col = (self.get("scoredLabelsCol")
                    if self.get("scoredLabelsCol") in df.schema else "prediction")

        def fn(p):
            y = np.asarray(p[label_col], dtype=np.float64)
            pred = np.asarray(p[pred_col], dtype=np.float64)
            return (pred - y) ** 2

        return df.with_column("squared_error", fn)


class MetricsLogger:
    """Metric emission (ComputeModelStatistics.scala:461-470 parity, both
    halves): every metric goes to the logging system AND into an obs
    MetricsRegistry as ``mmlspark_eval_metric{metric=...}`` gauges — the
    reference pushed into Spark's metrics sink; here the registry makes
    eval results scrapeable at ``/_mmlspark/metrics``, not just a returned
    DataFrame. Non-numeric values are logged but not gauged."""

    @staticmethod
    def log_metrics(metrics: Dict[str, Any], registry=None) -> None:
        from ..obs.metrics import default_registry

        reg = registry if registry is not None else default_registry()
        gauge = reg.gauge("mmlspark_eval_metric",
                          "last ComputeModelStatistics value per metric",
                          ("metric",))
        for k, v in metrics.items():
            log.info("metric %s=%s", k, v)
            try:
                gauge.labels(metric=str(k)).set(float(v))
            except (TypeError, ValueError):
                pass
