"""LIME: local interpretable model-agnostic explanations.

Reference: lime/LIME.scala — TabularLIME fits per-column statistics on a
background dataset (:164-190), TabularLIMEModel samples gaussian perturbations
per explained row, probes the inner model, and fits a per-row lasso (:191-220,
fitLasso at :158); ImageLIME does the same over superpixel on/off states
(:43-158). Here the probe batches go through the inner model's normal
``transform`` (jitted underneath) and the per-row lasso is the vmapped ISTA
kernel (ops/lasso.py) — explanations for a whole partition are a couple of
device launches.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import ComplexParam, HasInputCol, HasOutputCol, Param
from ..core.pipeline import Estimator, Model, Transformer
from ..core.schema import ColType, ImageSchema, Schema
from ..ops.lasso import fit_lasso
from .superpixel import Superpixel, slic


class TabularLIME(Estimator, HasInputCol, HasOutputCol):
    """Fit background statistics for tabular LIME (LIME.scala:164-190)."""

    model = ComplexParam("model", "The model stage to explain")
    predictionCol = Param("predictionCol", "Column with the model's output",
                          "prediction", ptype=str)
    nSamples = Param("nSamples", "Perturbation samples per row", 1000,
                     lambda v: v > 10, int)
    regularization = Param("regularization", "Lasso L1 strength", 0.0, ptype=float)
    samplingFraction = Param("samplingFraction", "Feature keep probability", 0.3,
                             ptype=float)
    seed = Param("seed", "Sampling seed", 0, ptype=int)

    def fit(self, df: DataFrame) -> "TabularLIMEModel":
        col = df.column(self.get_or_throw("inputCol"))
        X = np.stack([np.asarray(v, dtype=np.float64).reshape(-1) for v in col
                      if v is not None])
        return TabularLIMEModel(
            model=self.get_or_throw("model"),
            inputCol=self.get("inputCol"), outputCol=self.get("outputCol"),
            predictionCol=self.get("predictionCol"),
            nSamples=self.get("nSamples"),
            regularization=self.get("regularization"),
            seed=self.get("seed"),
            columnMeans=X.mean(axis=0), columnSTDs=X.std(axis=0) + 1e-12)


class TabularLIMEModel(Model, HasInputCol, HasOutputCol):
    model = ComplexParam("model", "The model stage to explain")
    columnMeans = ComplexParam("columnMeans", "Background feature means")
    columnSTDs = ComplexParam("columnSTDs", "Background feature stds")
    predictionCol = Param("predictionCol", "Model output column", "prediction",
                          ptype=str)
    nSamples = Param("nSamples", "Perturbation samples per row", 1000, ptype=int)
    regularization = Param("regularization", "Lasso L1 strength", 0.0, ptype=float)
    seed = Param("seed", "Sampling seed", 0, ptype=int)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        inner = self.get_or_throw("model")
        means = np.asarray(self.get_or_throw("columnMeans"), dtype=np.float64)
        stds = np.asarray(self.get_or_throw("columnSTDs"), dtype=np.float64)
        n_samples = self.get("nSamples")
        reg = self.get("regularization")
        rng = np.random.default_rng(self.get("seed"))
        d = len(means)

        def explain_rows(part):
            col = part[in_col]
            n = len(col)
            out = np.empty(n, dtype=object)
            for i in range(n):
                if col[i] is None:
                    out[i] = None
                    continue
                x0 = np.asarray(col[i], dtype=np.float64).reshape(-1)
                # gaussian perturbations in standardized space (LIME tabular)
                Z = rng.normal(size=(n_samples, d))
                Xp = x0[None, :] + Z * stds[None, :]
                probe_col = np.empty(n_samples, dtype=object)
                for s in range(n_samples):
                    probe_col[s] = Xp[s]
                probe_df = DataFrame([{in_col: probe_col}])
                scored = inner.transform(
                    probe_df.with_column_renamed(in_col, _inner_input(inner, in_col)))
                ys = _prediction_vector(scored, self.get("predictionCol"))
                w, _b = fit_lasso(Z.astype(np.float32), ys.astype(np.float32),
                                  np.float32(reg))
                # de-standardize: effect per original unit
                out[i] = np.asarray(w, dtype=np.float64) / stds
            part[out_col] = out
            return part

        return df.map_partitions(explain_rows)


def _inner_input(inner, default: str) -> str:
    for pname in ("featuresCol", "inputCol"):
        if inner.has_param(pname) and inner.get(pname):
            return inner.get(pname)
    return default


def _prediction_vector(scored: DataFrame, pred_col: str) -> np.ndarray:
    data = scored.collect()
    if pred_col not in data:
        raise KeyError(f"Prediction column {pred_col!r} missing; have "
                       f"{list(data)}")
    col = data[pred_col]
    if col.dtype == object:
        return np.array([float(np.asarray(v).reshape(-1)[-1]) for v in col])
    return col.astype(np.float64)


class ImageLIME(Transformer, HasInputCol, HasOutputCol):
    """Superpixel LIME for image models (LIME.scala:43-158)."""

    model = ComplexParam("model", "The image model stage to explain")
    predictionCol = Param("predictionCol", "Model output column", "prediction",
                          ptype=str)
    nSamples = Param("nSamples", "Mask samples per image", 100,
                     lambda v: v > 1, int)
    samplingFraction = Param("samplingFraction", "P(superpixel on)", 0.7,
                             ptype=float)
    regularization = Param("regularization", "Lasso L1 strength", 0.0, ptype=float)
    cellSize = Param("cellSize", "Superpixel spacing", 16.0, ptype=float)
    modifier = Param("modifier", "Superpixel color/space weight", 130.0, ptype=float)
    superpixelCol = Param("superpixelCol", "Output superpixel column", "superpixels",
                          ptype=str)
    seed = Param("seed", "Sampling seed", 0, ptype=int)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        inner = self.get_or_throw("model")
        n_samples = self.get("nSamples")
        frac = self.get("samplingFraction")
        reg = self.get("regularization")
        rng = np.random.default_rng(self.get("seed"))

        def explain(part):
            col = part[in_col]
            n = len(col)
            importances = np.empty(n, dtype=object)
            spx_col = np.empty(n, dtype=object)
            for i in range(n):
                row = col[i]
                if row is None:
                    importances[i] = None
                    spx_col[i] = None
                    continue
                img = ImageSchema.to_array(row) if ImageSchema.is_image(row) \
                    else np.asarray(row)
                labels = slic(img, self.get("cellSize"), self.get("modifier"))
                sp = Superpixel(labels)
                k = sp.num_clusters
                states = rng.random((n_samples, k)) < frac
                states[0] = True  # include the unmasked image
                probe_col = np.empty(n_samples, dtype=object)
                for s in range(n_samples):
                    probe_col[s] = ImageSchema.make(
                        sp.mask_image(img, states[s]).astype(img.dtype))
                probe_df = DataFrame([{in_col: probe_col}])
                scored = inner.transform(
                    probe_df.with_column_renamed(in_col, _inner_input(inner, in_col)))
                ys = _prediction_vector(scored, self.get("predictionCol"))
                w, _b = fit_lasso(states.astype(np.float32),
                                  ys.astype(np.float32), np.float32(reg))
                importances[i] = np.asarray(w, dtype=np.float64)
                spx_col[i] = {"labels": labels, "numClusters": k}
            part[out_col] = importances
            part[self.get("superpixelCol")] = spx_col
            return part

        return df.map_partitions(explain)
