"""SLIC-style superpixel clustering.

Reference: lime/Superpixel.scala:143+ — a SLIC variant clustering pixels by
(color, position) for ImageLIME's masking units. Implemented as vectorized
numpy k-means in (r,g,b,lambda*x,lambda*y) space with a fixed iteration count
(jit-friendly shape discipline; image sizes here are preprocessing-scale).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.params import HasInputCol, HasOutputCol, Param
from ..core.pipeline import Transformer
from ..core.schema import ColType, ImageSchema, Schema


def slic(img: np.ndarray, cell_size: float = 16.0, modifier: float = 130.0,
         iters: int = 5) -> np.ndarray:
    """HWC image -> int32 [H,W] superpixel labels (contiguous 0..K-1).

    ``cell_size``: target superpixel spacing in pixels; ``modifier``: color vs
    space weighting (reference Superpixel defaults 16 / 130).
    """
    img = np.asarray(img, dtype=np.float64)
    if img.ndim == 2:
        img = img[:, :, None]
    h, w, c = img.shape
    gy = max(1, int(round(h / cell_size)))
    gx = max(1, int(round(w / cell_size)))
    ys = (np.arange(gy) + 0.5) * h / gy
    xs = (np.arange(gx) + 0.5) * w / gx
    cy, cx = np.meshgrid(ys, xs, indexing="ij")
    centers_pos = np.stack([cy.ravel(), cx.ravel()], axis=1)       # [K,2]
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    pos = np.stack([yy.ravel(), xx.ravel()], axis=1).astype(np.float64)  # [P,2]
    colors = img.reshape(-1, c)                                    # [P,C]
    k = len(centers_pos)
    ci = np.clip(centers_pos[:, 0].astype(int), 0, h - 1)
    cj = np.clip(centers_pos[:, 1].astype(int), 0, w - 1)
    centers_col = img[ci, cj, :]
    space_w = modifier / cell_size

    labels = np.zeros(h * w, dtype=np.int64)
    for _ in range(iters):
        d_col = ((colors[:, None, :] - centers_col[None, :, :]) ** 2).sum(-1)
        d_pos = ((pos[:, None, :] - centers_pos[None, :, :]) ** 2).sum(-1)
        labels = np.argmin(d_col + (space_w ** 2) * d_pos, axis=1)
        for j in range(k):
            m = labels == j
            if m.any():
                centers_col[j] = colors[m].mean(axis=0)
                centers_pos[j] = pos[m].mean(axis=0)
    # compact labels
    uniq, labels = np.unique(labels, return_inverse=True)
    return labels.reshape(h, w).astype(np.int32)


class Superpixel:
    """Cluster container with masking helpers (Superpixel.scala parity)."""

    def __init__(self, labels: np.ndarray):
        self.labels = labels
        self.num_clusters = int(labels.max()) + 1 if labels.size else 0

    def mask_image(self, img: np.ndarray, states: np.ndarray,
                   background: float = 0.0) -> np.ndarray:
        """Zero out superpixels whose state is False (LIME's perturbation)."""
        keep = np.asarray(states, dtype=bool)[self.labels]
        out = np.array(img, copy=True)
        out[~keep] = background
        return out


class SuperpixelTransformer(Transformer, HasInputCol, HasOutputCol):
    """Image column -> superpixel struct column (lime/SuperpixelTransformer)."""

    cellSize = Param("cellSize", "Target superpixel spacing (px)", 16.0, ptype=float)
    modifier = Param("modifier", "Color/space weighting", 130.0, ptype=float)

    def __init__(self, **kwargs):
        kwargs.setdefault("outputCol", "superpixels")
        super().__init__(**kwargs)

    def transform(self, df: DataFrame) -> DataFrame:
        in_col = self.get_or_throw("inputCol")
        out_col = self.get_or_throw("outputCol")
        cell, mod = self.get("cellSize"), self.get("modifier")

        def fn(p):
            col = p[in_col]
            out = np.empty(len(col), dtype=object)
            for i, row in enumerate(col):
                if row is None:
                    out[i] = None
                    continue
                img = ImageSchema.to_array(row) if ImageSchema.is_image(row) \
                    else np.asarray(row)
                labels = slic(img, cell, mod)
                out[i] = {"labels": labels,
                          "numClusters": int(labels.max()) + 1}
            return out

        return df.with_column(out_col, fn)
