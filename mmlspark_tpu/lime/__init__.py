"""Model interpretability: LIME for tabular data and images (reference lime/).

TabularLIME/TabularLIMEModel (lime/LIME.scala:164-220), ImageLIME (superpixel
masking + sampled probes + per-row lasso, LIME.scala:43-158), SLIC superpixels
(lime/Superpixel.scala:143+), SuperpixelTransformer.
"""

from .superpixel import Superpixel, SuperpixelTransformer, slic
from .lime import ImageLIME, TabularLIME, TabularLIMEModel

__all__ = ["ImageLIME", "Superpixel", "SuperpixelTransformer", "TabularLIME",
           "TabularLIMEModel", "slic"]
